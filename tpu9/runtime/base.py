"""Container runtime abstraction.

Reference analogue: the ``Runtime`` interface
(``pkg/runtime/runtime.go:87-128``: Run/Exec/Kill/Delete/State/Events/
Checkpoint/Restore/Capabilities) backed by runc/runsc/docker. tpu9 ships two
implementations:

- :class:`tpu9.runtime.process.ProcessRuntime` — containers as supervised
  host processes in per-container sandboxes (rootless dev/test/bench path;
  also how BYOC hosts without runc run).
- :class:`tpu9.runtime.runc.RuncRuntime` — OCI containers via a runc binary
  with synthesized specs (the production path on TPU VM workers).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class RuntimeState(str, enum.Enum):
    CREATING = "creating"
    RUNNING = "running"
    STOPPED = "stopped"
    FAILED = "failed"


@dataclass
class ContainerSpec:
    """Runtime-agnostic spec, synthesized by the worker lifecycle from a
    ContainerRequest (analogue of OCI-spec synthesis, lifecycle.go:766)."""

    container_id: str
    entrypoint: list[str]
    env: dict[str, str] = field(default_factory=dict)
    workdir: str = "/"
    rootfs: str = ""                  # image bundle dir ("" = host fs)
    mounts: list[tuple[str, str, bool]] = field(default_factory=list)  # (src, dst, ro)
    cpu_millicores: int = 0
    memory_mb: int = 0
    devices: list[str] = field(default_factory=list)   # e.g. /dev/accel0
    ports: dict[int, int] = field(default_factory=dict)  # container -> host
    # env keys the WORKER injected that carry control-plane loopback URLs
    # (gateway, gang coordinator). Only these may be rewritten to the veth
    # host IP / get an outbound reverse proxy — user-supplied TPU9_* env
    # must never open tunnels out of the netns (tenant isolation).
    cp_env_keys: list[str] = field(default_factory=list)
    # unprivileged identity the workload drops to after namespace/mount
    # setup (0 = stay root; TPU containers need root to open /dev/accel*).
    # Seccomp + capability-bounding drop + no_new_privs apply either way
    # (reference analogue: base_runc_config.json's hardened spec + gVisor).
    run_as_uid: int = 0
    run_as_gid: int = 0
    # seccomp polarity: "" = binary default (allow-list, VERDICT r04 #2);
    # "deny" = legacy deny-list fallback for user images whose syscall
    # needs outrun the recorded trace; "off" = debugging only
    seccomp_mode: str = ""


@dataclass
class ContainerHandle:
    container_id: str
    pid: int = 0
    state: RuntimeState = RuntimeState.CREATING
    exit_code: Optional[int] = None
    meta: dict[str, Any] = field(default_factory=dict)


class ShellSession:
    """An interactive exec attached to a PTY inside a container (reference:
    the shell abstraction starts dropbear in-container, shell/shell.go:53;
    tpu9 attaches a PTY through the runtime instead — no sshd needed).

    ``output`` yields bytes chunks until process exit (None terminator);
    ``write`` feeds the PTY's input; ``resize`` propagates terminal size."""

    def __init__(self) -> None:
        import asyncio
        self.output: "asyncio.Queue[Optional[bytes]]" = asyncio.Queue()
        self.exit_code: Optional[int] = None

    async def write(self, data: bytes) -> None:
        raise NotImplementedError

    def resize(self, rows: int, cols: int) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        raise NotImplementedError


class Runtime:
    name = "base"

    async def run(self, spec: ContainerSpec, log_cb=None) -> ContainerHandle:
        """Start the container; ``log_cb(line, stream)`` receives output."""
        raise NotImplementedError

    async def kill(self, container_id: str, signal_num: int = 15) -> bool:
        raise NotImplementedError

    async def state(self, container_id: str) -> Optional[ContainerHandle]:
        raise NotImplementedError

    async def wait(self, container_id: str) -> int:
        """Block until exit; returns exit code."""
        raise NotImplementedError

    async def exec(self, container_id: str, cmd: list[str]) -> tuple[int, str]:
        raise NotImplementedError

    async def exec_stream(self, container_id: str,
                          cmd: Optional[list[str]] = None) -> ShellSession:
        """Interactive PTY exec in the container (tpu9 shell)."""
        raise NotImplementedError

    def fs_root(self, container_id: str) -> Optional[str]:
        """Host-visible path of the container's working tree (the sandbox
        fs API operates here: upload/download/ls without exec round-trips).
        None when the container is unknown."""
        return None

    def capabilities(self) -> set[str]:
        return set()
