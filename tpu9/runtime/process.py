"""Process runtime: containers as supervised host subprocesses.

Each container gets a private sandbox dir (scratch + workspace), its env is
fully specified (no inheritance beyond an allowlist), stdout/stderr stream to
the worker's log callback, and resource limits are applied via RLIMIT where
the platform allows. This is the rootless path the test suite, the bench
cold-start harness, and dev machines use; runc swaps in transparently on
real workers (same ContainerSpec).
"""

from __future__ import annotations

import asyncio
import os
import shutil
import signal
import sys
from typing import Optional

from .base import (ContainerHandle, ContainerSpec, Runtime, RuntimeState,
                   ShellSession)
from .zygote_client import ZygoteClient
from ..utils.aio import cancellable_wait, spawn

_ENV_ALLOWLIST = ("PATH", "HOME", "LANG", "TERM")

# runner modules eligible for zygote (pre-warmed fork) starts. llm/build
# are excluded: llm containers dial accelerators with env the fork must
# not half-inherit, builds run arbitrary shell.
_ZYGOTE_MODULES = ("tpu9.runner.endpoint", "tpu9.runner.taskqueue",
                   "tpu9.runner.function")


class ProcessRuntime(Runtime):
    name = "process"

    def __init__(self, base_dir: str = "/tmp/tpu9/containers") -> None:
        self.base_dir = base_dir
        self._procs: dict[str, asyncio.subprocess.Process] = {}
        self._handles: dict[str, ContainerHandle] = {}
        self._waiters: dict[str, asyncio.Task] = {}
        self._log_tasks: dict[str, list[asyncio.Task]] = {}
        self._specs: dict[str, ContainerSpec] = {}
        # pre-warmed fork-server (VERDICT r03 #4): jax/numpy/aiohttp are
        # imported once per worker, runner containers fork from it.
        # TPU9_ZYGOTE=0 disables.
        self._zygote: ZygoteClient | None = None
        if os.environ.get("TPU9_ZYGOTE", "1") != "0":
            self._zygote = ZygoteClient(
                os.path.join(base_dir, ".zygote.sock"))

    def sandbox_dir(self, container_id: str) -> str:
        return os.path.join(self.base_dir, container_id)

    def _zygote_module(self, spec: ContainerSpec) -> str:
        """The runner module to fork for this spec, or '' for exec path."""
        ep = spec.entrypoint
        if (self._zygote is not None and len(ep) == 3
                and ep[0] == sys.executable and ep[1] == "-m"
                and ep[2] in _ZYGOTE_MODULES
                and "LD_PRELOAD" not in spec.env):
            # LD_PRELOAD (vcache/lazy shims) needs a fresh exec to take
            # effect — a fork inherits the zygote's (shimless) libc state
            return ep[2]
        return ""

    async def run(self, spec: ContainerSpec, log_cb=None) -> ContainerHandle:
        sandbox = self.sandbox_dir(spec.container_id)
        os.makedirs(sandbox, exist_ok=True)

        env = {k: v for k in _ENV_ALLOWLIST
               if (v := os.environ.get(k)) is not None}
        env.update(spec.env)
        env.setdefault("TPU9_SANDBOX", sandbox)

        workdir = spec.workdir if spec.workdir not in ("", "/") else sandbox

        def preexec() -> None:
            os.setsid()  # own process group so kill() reaps the whole tree
            # NOTE: no RLIMIT_AS — jax/TF reserve address space far beyond
            # their RSS, so an AS cap spuriously kills ML containers at
            # import. Memory is enforced as RSS by the worker's OOM watcher
            # (reference pkg/runtime/oom_watcher.go), which SIGKILLs over-
            # limit containers → exit 137 → normalized to an OOM stop reason.

        proc = None
        module = self._zygote_module(spec)
        if module and await self._zygote.ensure_started():
            try:
                proc = await self._zygote.spawn(env, workdir, module)
            except Exception as exc:        # noqa: BLE001 — fall back
                import logging
                logging.getLogger("tpu9.worker").warning(
                    "zygote spawn failed (%s); exec fallback", exc)
                proc = None
        if proc is None:
            proc = await asyncio.create_subprocess_exec(
                *spec.entrypoint, cwd=workdir, env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
                preexec_fn=preexec)

        handle = ContainerHandle(container_id=spec.container_id, pid=proc.pid,
                                 state=RuntimeState.RUNNING)
        self._procs[spec.container_id] = proc
        self._handles[spec.container_id] = handle
        self._specs[spec.container_id] = spec

        async def pump(stream, name):
            while True:
                line = await stream.readline()
                if not line:
                    break
                if log_cb is not None:
                    try:
                        log_cb(line.decode(errors="replace").rstrip("\n"), name)
                    except Exception:
                        pass

        self._log_tasks[spec.container_id] = [
            asyncio.create_task(pump(proc.stdout, "stdout")),
            asyncio.create_task(pump(proc.stderr, "stderr")),
        ]

        async def reap():
            code = await proc.wait()
            tasks = self._log_tasks.get(spec.container_id, [])
            if tasks:
                # asyncio.wait (ASY003/ASY001): never consumes a child's
                # error or converts OUR cancel into a return — a cancelled
                # reap stops updating state instead of half-finishing
                done, pending = await asyncio.wait(tasks, timeout=2.0)
                for t in pending:
                    t.cancel()
                for t in done:
                    if not t.cancelled():
                        exc = t.exception()
                        if exc is not None:
                            # readline/decode failures (pump only guards
                            # the log_cb call) — log loss must be visible
                            import logging
                            logging.getLogger("tpu9.worker").warning(
                                "log pump for %s died: %r",
                                spec.container_id, exc)
            handle.exit_code = code
            handle.state = (RuntimeState.STOPPED if code == 0
                            else RuntimeState.FAILED)

        self._waiters[spec.container_id] = asyncio.create_task(reap())
        return handle

    async def kill(self, container_id: str, signal_num: int = 15) -> bool:
        proc = self._procs.get(container_id)
        if proc is None or proc.returncode is not None:
            return False
        try:
            os.killpg(os.getpgid(proc.pid), signal_num)
        except ProcessLookupError:
            return False
        if signal_num != signal.SIGKILL:
            # escalate if it ignores the polite signal — STRONG ref: the
            # loop only weak-refs tasks, and a GC'd escalation would let a
            # SIGTERM-trapping container live forever while the scheduler
            # believes it stopped
            async def escalate():
                try:
                    # cancellable_wait, not wait_for: a cancel racing the
                    # exit must cancel the escalation, not be swallowed
                    await cancellable_wait(proc.wait(), timeout=10.0)
                except asyncio.TimeoutError:
                    try:
                        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            spawn(escalate(), name=f"kill-escalate-{container_id[-8:]}")
        return True

    async def state(self, container_id: str) -> Optional[ContainerHandle]:
        return self._handles.get(container_id)

    async def wait(self, container_id: str) -> int:
        proc = self._procs.get(container_id)
        if proc is None:
            handle = self._handles.get(container_id)
            return handle.exit_code if handle and handle.exit_code is not None else -1
        code = await proc.wait()
        waiter = self._waiters.get(container_id)
        if waiter:
            # shield: reap owns the container's TERMINAL state transition
            # and is shared by every wait() caller — cancelling one caller
            # must not cancel it (pre-existing hazard: the bare `await
            # waiter` propagated the cancel INTO reap, stranding
            # handle.state RUNNING forever). gather (ASY003): our cancel
            # still reaches the caller; a CRASHED reap keeps propagating
            # like it always did (its state updates never ran).
            res = (await asyncio.gather(asyncio.shield(waiter),
                                        return_exceptions=True))[0]
            if (isinstance(res, BaseException)
                    and not isinstance(res, asyncio.CancelledError)):
                raise res
        return code

    def _exec_cwd(self, container_id: str) -> str:
        """Exec runs where the container's entrypoint does (its workdir,
        where volume/disk mounts are linked), not the runtime scratch dir."""
        spec = self._specs.get(container_id)
        if spec is not None and spec.workdir not in ("", "/"):
            return spec.workdir
        return self.sandbox_dir(container_id)

    def fs_root(self, container_id: str):
        if container_id not in self._handles:
            return None
        return self._exec_cwd(container_id)

    async def exec(self, container_id: str, cmd: list[str]) -> tuple[int, str]:
        """Run a command in the container's sandbox/env context."""
        handle = self._handles.get(container_id)
        if handle is None or handle.state != RuntimeState.RUNNING:
            return (-1, "container not running")
        spec = self._specs.get(container_id)
        env = {k: v for k in _ENV_ALLOWLIST
               if (v := os.environ.get(k)) is not None}
        if spec is not None:
            env.update(spec.env)
        proc = await asyncio.create_subprocess_exec(
            *cmd, cwd=self._exec_cwd(container_id), env=env,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
        out, _ = await proc.communicate()
        return (proc.returncode or 0, out.decode(errors="replace"))

    async def exec_stream(self, container_id: str,
                          cmd: Optional[list[str]] = None) -> "_PtySession":
        """Interactive PTY exec in the container's sandbox/env context
        (the `tpu9 shell` transport)."""
        handle = self._handles.get(container_id)
        if handle is None or handle.state != RuntimeState.RUNNING:
            raise RuntimeError("container not running")
        spec = self._specs.get(container_id)
        env = {k: v for k in _ENV_ALLOWLIST
               if (v := os.environ.get(k)) is not None}
        if spec is not None:
            env.update(spec.env)
        env.setdefault("TERM", "xterm")
        env["PS1"] = r"tpu9:\w$ "
        cmd = cmd or [shutil.which("bash") or "/bin/sh", "-i"]

        import pty as _pty
        master, slave = _pty.openpty()
        proc = await asyncio.create_subprocess_exec(
            *cmd, cwd=self._exec_cwd(container_id), env=env,
            stdin=slave, stdout=slave, stderr=slave,
            preexec_fn=os.setsid, close_fds=True)
        os.close(slave)
        return _PtySession(master, proc)

    async def cleanup(self, container_id: str, remove_sandbox: bool = True) -> None:
        self._procs.pop(container_id, None)
        self._handles.pop(container_id, None)
        self._specs.pop(container_id, None)
        waiter = self._waiters.pop(container_id, None)
        if waiter:
            waiter.cancel()
        for t in self._log_tasks.pop(container_id, []):
            t.cancel()
        if remove_sandbox:
            shutil.rmtree(self.sandbox_dir(container_id), ignore_errors=True)

    def capabilities(self) -> set[str]:
        return {"exec", "exec_stream", "logs"}


class _PtySession(ShellSession):
    """PTY master wired into the event loop; output chunks land on the
    queue, writes go straight to the master fd."""

    def __init__(self, master_fd: int, proc: asyncio.subprocess.Process):
        super().__init__()
        self._fd = master_fd
        self._proc = proc
        self._loop = asyncio.get_running_loop()
        self._closed = False
        self._finished = False
        self._loop.add_reader(master_fd, self._on_readable)
        self._exit_task = asyncio.create_task(self._watch_exit())

    def _on_readable(self) -> None:
        try:
            data = os.read(self._fd, 65536)
        except OSError:          # EIO: slave side closed (process exited)
            data = b""
        if data:
            self.output.put_nowait(data)
        else:
            # fd EOF only closes the pipe; the None terminator comes from
            # the exit watcher AFTER exit_code is known — otherwise the
            # consumer reads the terminator with exit_code still unset
            self._close_fd()

    async def _watch_exit(self) -> None:
        self.exit_code = await self._proc.wait()
        # give the reader a beat to drain buffered output, then finish
        await asyncio.sleep(0.05)
        self._close_fd()
        self._finish()

    def _close_fd(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._loop.remove_reader(self._fd)
            os.close(self._fd)
        except OSError:
            pass

    def _finish(self) -> None:
        if not self._finished:
            self._finished = True
            self.output.put_nowait(None)

    async def write(self, data: bytes) -> None:
        if not self._closed:
            try:
                os.write(self._fd, data)
            except OSError:
                self._close_fd()

    def resize(self, rows: int, cols: int) -> None:
        if self._closed:
            return
        import fcntl
        import struct
        import termios
        try:
            fcntl.ioctl(self._fd, termios.TIOCSWINSZ,
                        struct.pack("HHHH", rows, cols, 0, 0))
        except OSError:
            pass

    async def close(self) -> None:
        if self._proc.returncode is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        self._close_fd()
        # the exit watcher records the code and emits the terminator
