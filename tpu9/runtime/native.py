"""NativeRuntime: real container isolation via the t9container binary.

Reference analogue: the patched-runc path (``pkg/runtime/runc.go`` + the
``beam-cloud/runc`` fork) and the per-container network manager
(``pkg/worker/network.go:64,193-215,275-399`` — netns + veth + port
forwarding + egress blocking). tpu9 implements the same containment
natively instead of shelling out to an OCI runtime:

- namespaces (pid/mount/uts/ipc) + pivot_root via ``native/t9container``
- per-container network namespace with a /30 veth pair; egress beyond the
  host is blocked by construction (no NAT, no default route)
- userspace host→container port proxy (the reference's agent port proxy,
  ``container_port_proxy.go``), so discovery/probes keep using
  127.0.0.1:<port> exactly like the process runtime
- rootfs: OCI image snapshots get an overlayfs upper over the pulled
  ``rootfs/`` tree (lifecycle.go:1996's createOverlay analogue); env
  snapshots get a host-backed root (RO system binds + RW sandbox)

Root required; ``NativeRuntime.supported()`` gates tests and factory use.
"""

from __future__ import annotations

import asyncio
import errno
import json
import logging
import os
import re
import shutil
import signal
import time
from typing import Optional

from .base import ContainerHandle, ContainerSpec, Runtime, RuntimeState

log = logging.getLogger("tpu9.runtime")

from ..utils import native_binary
from ..utils.aio import cancellable_wait, spawn

_NATIVE_BIN = native_binary("t9container")

# host dirs bound read-only into env-snapshot containers (the "image" only
# overlays the python env; the OS comes from the host like ProcessRuntime,
# but now behind a private mount namespace + pivot_root)
_SYSTEM_BINDS = ("/usr", "/bin", "/sbin", "/lib", "/lib64", "/etc", "/opt")


def _rewrite_cp_env(env: dict, cp_env_keys, host_ip: str) -> set[int]:
    """Rewrite control-plane loopback URLs to the veth host IP, returning
    the loopback ports that need an outbound reverse proxy.

    SECURITY: only worker-injected control-plane keys (spec.cp_env_keys) are
    eligible — the rest of env is tenant-controlled, and a tenant setting
    TPU9_X=http://127.0.0.1:<p> must NOT get a tunnel out of its netns to
    host-loopback services (other tenants' port proxies, worker internals)."""
    cp_ports: set[int] = set()
    for key in cp_env_keys:
        val = env.get(key)
        if isinstance(val, str) and "127.0.0.1" in val:
            env[key] = val.replace("127.0.0.1", host_ip)
            cp_ports.update(int(p) for p in
                            re.findall(r"127\.0\.0\.1:(\d+)", val))
    return cp_ports


def _chown_tree(path: str, uid: int, gid: int) -> None:
    """Recursive chown that never follows symlinks (a tenant-supplied link
    in a workspace must not redirect the chown onto host files). Walks the
    whole tree every start — the root worker may have ADDED files (volume
    sync) since the last handoff, so a top-dir completion marker would
    strand them root-owned — but only dirties inodes whose owner actually
    differs, so the warm-restart walk is pure metadata reads."""
    try:
        st = os.lstat(path)
        if st.st_uid != uid or st.st_gid != gid:
            os.lchown(path, uid, gid)
    except OSError:
        return
    for root, dirs, files in os.walk(path):
        for name in dirs + files:
            p = os.path.join(root, name)
            try:
                st = os.lstat(p)
                # BOTH ids: a matching uid with a stale gid (redeploy with
                # a new run_as_gid) would skip the fix and break group-
                # permission workloads inside the container
                if st.st_uid != uid or st.st_gid != gid:
                    os.lchown(p, uid, gid)
            except OSError:
                continue


def _run(cmd: list[str]) -> None:
    import subprocess
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"{' '.join(cmd)}: {proc.stderr.strip()}")


class NativeRuntime(Runtime):
    name = "native"

    def __init__(self, base_dir: str = "/tmp/tpu9/native",
                 subnet_base: str = "10.77"):
        self.base_dir = base_dir
        self.subnet_base = subnet_base
        if self.supported():
            swept = self.sweep_orphans()
            if swept:
                log.info("swept %d orphaned container netns", swept)
        self._procs: dict[str, asyncio.subprocess.Process] = {}
        self._handles: dict[str, ContainerHandle] = {}
        self._specs: dict[str, ContainerSpec] = {}
        self._log_tasks: dict[str, list[asyncio.Task]] = {}
        self._proxies: dict[str, list[asyncio.base_events.Server]] = {}
        # reap tasks by container: wait() awaits the FULL teardown
        # (proxies closed, netns gone, overlay unmounted), not just the
        # process exit — callers that mark a container stopped on wait()
        # (lifecycle._supervise → scale-down/bench teardown) must not
        # race the unmount of a bundle they are about to delete
        self._waiters: dict[str, asyncio.Task] = {}
        self._slots: dict[str, int] = {}      # container -> /30 slot index
        self._ifnames: dict[str, str] = {}    # container -> host veth name

    @staticmethod
    def supported() -> bool:
        return (os.geteuid() == 0 and os.path.exists(_NATIVE_BIN)
                and shutil.which("ip") is not None)

    @staticmethod
    def sweep_orphans() -> int:
        """Delete t9-* network namespaces with no live processes — leftovers
        of workers that died before their reap tasks ran (the netns is host
        state and outlives the worker process). Deleting the netns tears
        down its veth pair end-to-end. Called at worker startup, like the
        reference's preallocated-slot reconciliation (network.go:193)."""
        import subprocess
        out = subprocess.run(["ip", "netns", "list"], capture_output=True,
                             text=True).stdout
        removed = 0
        for line in out.splitlines():
            ns = line.split()[0] if line.split() else ""
            if not ns.startswith("t9-"):
                continue
            # age gate: another runtime may have just created this netns and
            # not yet started its container — only reap cold leftovers
            try:
                age = time.time() - os.stat(f"/run/netns/{ns}").st_ctime
            except OSError:
                continue
            if age < 120.0:
                continue
            pids = subprocess.run(["ip", "netns", "pids", ns],
                                  capture_output=True, text=True).stdout
            if not pids.strip():
                subprocess.run(["ip", "netns", "del", ns],
                               capture_output=True)
                removed += 1
        return removed

    # -- paths / net ---------------------------------------------------------

    def sandbox_dir(self, container_id: str) -> str:
        return os.path.join(self.base_dir, container_id)

    def _netns(self, container_id: str) -> str:
        return f"t9-{container_id[-12:]}"

    def _ips(self, slot: int) -> tuple[str, str]:
        """(host, container) addrs of the /30 for this slot."""
        hi, lo = divmod(slot, 64)
        base = 4 * lo
        return (f"{self.subnet_base}.{hi}.{base + 1}",
                f"{self.subnet_base}.{hi}.{base + 2}")

    def _setup_net(self, container_id: str) -> tuple[str, str]:
        """Slot (veth names + /30 subnet) derives from the container id so
        multiple NativeRuntime instances on one host (multi-worker tests,
        several worker processes) can't collide on 't9h1'; hash collisions
        retry with a salt."""
        import hashlib
        ns = self._netns(container_id)
        last_err: Optional[Exception] = None
        for salt in range(8):
            digest = hashlib.sha1(
                f"{container_id}:{salt}".encode()).hexdigest()
            slot = int(digest[:6], 16) % 16000
            # the ifname ENCODES the slot: two containers hashing to the
            # same /30 collide on the interface name and retry with a new
            # salt, instead of silently double-assigning the same IPs
            host_if = f"t9h{slot}"
            cont_if = f"t9c{slot}"
            host_ip, cont_ip = self._ips(slot)
            try:
                _run(["ip", "netns", "add", ns])
            except RuntimeError as exc:
                if "File exists" not in str(exc):
                    raise
            try:
                _run(["ip", "link", "add", host_if, "type", "veth",
                      "peer", "name", cont_if])
                _run(["ip", "link", "set", cont_if, "netns", ns])
                _run(["ip", "addr", "add", f"{host_ip}/30", "dev", host_if])
                _run(["ip", "link", "set", host_if, "up"])
                _run(["ip", "netns", "exec", ns, "ip", "addr", "add",
                      f"{cont_ip}/30", "dev", cont_if])
                _run(["ip", "netns", "exec", ns, "ip", "link", "set",
                      cont_if, "up"])
                _run(["ip", "netns", "exec", ns, "ip", "link", "set",
                      "lo", "up"])
            except RuntimeError as exc:
                last_err = exc
                import subprocess
                subprocess.run(["ip", "link", "del", host_if],
                               capture_output=True)
                continue
            self._slots[container_id] = slot
            self._ifnames[container_id] = host_if
            # deliberately NO default route and NO NAT: the container
            # reaches the host side of its veth (gateway, cache) and
            # nothing else — egress blocking by construction
            # (network.go:275's BlockNetwork)
            return host_ip, cont_ip
        raise RuntimeError(f"veth setup failed for {container_id}: "
                           f"{last_err}")

    def _teardown_net(self, container_id: str) -> None:
        import subprocess
        self._slots.pop(container_id, None)
        ifname = self._ifnames.pop(container_id, None)
        if ifname:
            subprocess.run(["ip", "link", "del", ifname],
                           capture_output=True)
        subprocess.run(["ip", "netns", "del", self._netns(container_id)],
                       capture_output=True)

    async def _proxy_port(self, container_id: str, host_port: int,
                          cont_ip: str, cont_port: int,
                          listen_host: str = "127.0.0.1") -> None:
        """Userspace forward listen_host:host_port → cont_ip:cont_port."""
        async def handle(reader, writer):
            try:
                up_r, up_w = await asyncio.open_connection(cont_ip, cont_port)
            except OSError:
                writer.close()
                return

            async def pump(src, dst):
                try:
                    while True:
                        data = await src.read(65536)
                        if not data:
                            break
                        dst.write(data)
                        await dst.drain()
                except ConnectionError:
                    pass        # peer went away: close our side (finally)
                except asyncio.CancelledError:
                    raise       # proxy teardown — propagate (ASY003)
                finally:
                    try:
                        dst.close()
                    except Exception:
                        pass

            await asyncio.gather(pump(reader, up_w), pump(up_r, writer),
                                 return_exceptions=True)

        server = await asyncio.start_server(handle, listen_host, host_port)
        self._proxies.setdefault(container_id, []).append(server)

    # -- rootfs --------------------------------------------------------------

    def _prepare_rootfs(self, spec: ContainerSpec,
                        sandbox: str) -> tuple[str, list[str]]:
        """Returns (rootfs_dir, extra --bind specs)."""
        binds: list[str] = []
        bundle = spec.rootfs
        is_oci = False
        if bundle:
            meta = os.path.join(bundle, ".tpu9-env.json")
            try:
                with open(meta) as f:
                    is_oci = json.load(f).get("kind") == "oci"
            except (OSError, ValueError):
                pass
        if is_oci:
            # overlay upper over the pulled image tree: container writes
            # never touch the shared bundle (lifecycle.go:1996)
            lower = os.path.join(bundle, "rootfs")
            upper = os.path.join(sandbox, "overlay-upper")
            work = os.path.join(sandbox, "overlay-work")
            merged = os.path.join(sandbox, "rootfs")
            for d in (upper, work, merged):
                os.makedirs(d, exist_ok=True)
            _run(["mount", "-t", "overlay", "overlay",
                  "-o", f"lowerdir={lower},upperdir={upper},workdir={work}",
                  merged])
            return merged, binds
        # env snapshot / no image: host-backed root behind a private mount
        # ns. The bundle and workdir keep their HOST paths inside the
        # container — the lifecycle computed PYTHONPATH/TPU9_IMAGE_SITE
        # against those absolute paths
        root = os.path.join(sandbox, "rootfs")
        os.makedirs(root, exist_ok=True)
        for d in _SYSTEM_BINDS:
            if os.path.isdir(d):
                binds.append(f"{d}:{d}:ro")
        # the tpu9 package itself: runner entrypoints import it by absolute
        # path (the lifecycle appends this root to PYTHONPATH)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if not any(repo_root.startswith(d + os.sep) or repo_root == d
                   for d in _SYSTEM_BINDS):
            binds.append(f"{repo_root}:{repo_root}:ro")
        if bundle:
            binds.append(f"{bundle}:{bundle}:ro")
        return root, binds

    # -- Runtime interface ---------------------------------------------------

    async def run(self, spec: ContainerSpec, log_cb=None) -> ContainerHandle:
        try:
            return await self._run_inner(spec, log_cb)
        except BaseException:
            # failure-path teardown: a raise after _setup_net (netns/veth)
            # or after the process spawned would otherwise leak the netns,
            # overlay mounts and proxies AND strand the handle RUNNING
            # (the lifecycle's failure path only runtime.kill()s)
            proc = self._procs.get(spec.container_id)
            if proc is not None and proc.returncode is None:
                try:
                    os.killpg(os.getpgid(proc.pid), 9)
                except (ProcessLookupError, PermissionError):
                    pass
            try:
                await self.cleanup(spec.container_id, remove_sandbox=False)
            except Exception:       # noqa: BLE001 — best-effort teardown
                pass
            raise

    async def _run_inner(self, spec: ContainerSpec,
                         log_cb=None) -> ContainerHandle:
        sandbox = self.sandbox_dir(spec.container_id)
        os.makedirs(sandbox, exist_ok=True)

        # netns/overlay setup shells out to `ip`/`mount` — off the loop,
        # or every container start stalls heartbeats and every other
        # container's proxies/log pumps
        host_ip, cont_ip = await asyncio.to_thread(self._setup_net,
                                                   spec.container_id)
        rootfs, binds = await asyncio.to_thread(self._prepare_rootfs,
                                                spec, sandbox)

        env = dict(spec.env)
        env.setdefault("PATH", "/usr/local/bin:/usr/bin:/bin")
        env.setdefault("HOME", "/root")
        # the runner must bind an interface the veth proxy can reach
        env["TPU9_BIND_HOST"] = "0.0.0.0"
        env["TPU9_HOST_IP"] = host_ip      # the veth's host side
        # 127.0.0.1 means "this netns" inside the container: control-plane
        # URLs the worker injected must point at the host side of the veth
        # — AND something must be listening there. Control-plane services
        # (gateway, cache) bind the host's loopback, so for every rewritten
        # port a reverse proxy on host_ip forwards into 127.0.0.1 of the
        # host netns (outbound analogue of the inbound port proxy; the
        # reference's agent route-proxy plays the same role).
        cp_ports = _rewrite_cp_env(env, spec.cp_env_keys, host_ip)
        for port in sorted(cp_ports):
            try:
                await self._proxy_port(spec.container_id, port,
                                       "127.0.0.1", port,
                                       listen_host=host_ip)
            except OSError as exc:
                if exc.errno != errno.EADDRINUSE:
                    raise
                # EADDRINUSE alone is benign: a prior container on this /30
                # slot left its proxy up forwarding to the same place

        workdir = spec.workdir or "/"
        if workdir not in ("", "/"):
            # the lifecycle's workspace dir rides into the container at its
            # host path, read-write
            binds.append(f"{workdir}:{workdir}")
        if spec.run_as_uid:
            # the dropped identity can't read /root — point HOME (pip/HF/
            # JAX caches all key off it) at the tenant's write surface
            env["HOME"] = workdir if workdir not in ("", "/") else "/tmp"
        env_file = os.path.join(sandbox, ".t9env")
        with open(env_file, "wb") as f:
            for k, v in env.items():
                f.write(f"{k}={v}".encode() + b"\0")

        cmd = [_NATIVE_BIN, "--rootfs", rootfs, "--workdir", workdir,
               "--hostname", spec.container_id[:32],
               "--netns", self._netns(spec.container_id),
               "--env-file", env_file]
        if spec.seccomp_mode:
            cmd += ["--seccomp-mode", spec.seccomp_mode]
        if spec.run_as_uid or spec.run_as_gid:
            cmd += ["--uid", str(spec.run_as_uid),
                    "--gid", str(spec.run_as_gid)]
            # the tenant's write surfaces — workspace workdir plus rw
            # volume/disk mounts (all extracted/created by the root worker)
            # are handed to the dropped identity; ro binds stay root-owned.
            # In an executor: weight-sized trees must not stall the worker's
            # event loop (heartbeats, other containers' proxies).
            loop = asyncio.get_running_loop()
            targets = [workdir] if workdir not in ("", "/") \
                and os.path.isdir(workdir) else []
            targets += [src for src, _dst, ro in spec.mounts
                        if not ro and os.path.isdir(src)]
            for target in targets:
                await loop.run_in_executor(
                    None, _chown_tree, target,
                    spec.run_as_uid, spec.run_as_gid)
        for b in binds:
            cmd += ["--bind", b]
        for mount_src, mount_dst, ro in spec.mounts:
            cmd += ["--bind",
                    f"{mount_src}:{mount_dst}{':ro' if ro else ''}"]
        for dev in spec.devices:
            cmd += ["--dev", dev]
        cmd += ["--"] + list(spec.entrypoint)

        proc = await asyncio.create_subprocess_exec(
            *cmd, stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            preexec_fn=os.setsid)

        handle = ContainerHandle(container_id=spec.container_id,
                                 pid=proc.pid, state=RuntimeState.RUNNING,
                                 meta={"host_ip": host_ip,
                                       "cont_ip": cont_ip})
        self._procs[spec.container_id] = proc
        self._handles[spec.container_id] = handle
        self._specs[spec.container_id] = spec

        async def pump(stream, name):
            while True:
                line = await stream.readline()
                if not line:
                    break
                if log_cb is not None:
                    try:
                        log_cb(line.decode(errors="replace").rstrip("\n"),
                               name)
                    except Exception:
                        pass

        self._log_tasks[spec.container_id] = [
            asyncio.create_task(pump(proc.stdout, "stdout")),
            asyncio.create_task(pump(proc.stderr, "stderr")),
        ]

        # host-port → container-port proxies (same port number inside)
        for cont_port, host_port in (spec.ports or {}).items():
            await self._proxy_port(spec.container_id, host_port or cont_port,
                                   cont_ip, cont_port)

        async def reap():
            code = await proc.wait()
            handle.exit_code = code
            handle.state = (RuntimeState.STOPPED if code == 0
                            else RuntimeState.FAILED)
            await self._close_proxies(spec.container_id)
            await asyncio.to_thread(self._teardown_net, spec.container_id)
            await asyncio.to_thread(self._cleanup_mounts,
                                    spec.container_id)

        # spawn: strong ref (a GC'd reap would leak the netns/veth/overlay
        # of a dead container) + crash logging; also registered as the
        # container's waiter so wait() returns only after the teardown
        # (coldstart_native flake: scale-down marked the container gone
        # while this task was still unmounting the overlay, and the next
        # trial's rmtree/mount of the same bundle raced it)
        self._waiters[spec.container_id] = spawn(
            reap(), name=f"native-reap-{spec.container_id[-8:]}")
        return handle

    async def _close_proxies(self, container_id: str) -> None:
        for server in self._proxies.pop(container_id, []):
            server.close()
            try:
                await server.wait_closed()
            except Exception:
                pass

    def _cleanup_mounts(self, container_id: str) -> None:
        merged = os.path.join(self.sandbox_dir(container_id), "rootfs")
        import subprocess
        subprocess.run(["umount", "-l", merged], capture_output=True)

    def _container_pid(self, container_id: str) -> Optional[int]:
        """PID of the container's init (t9container's child)."""
        proc = self._procs.get(container_id)
        if proc is None or proc.returncode is not None:
            return None
        try:
            kids = open(f"/proc/{proc.pid}/task/{proc.pid}/children").read()
            return int(kids.split()[0]) if kids.split() else None
        except (OSError, ValueError, IndexError):
            return None

    async def kill(self, container_id: str, signal_num: int = 15) -> bool:
        proc = self._procs.get(container_id)
        if proc is None or proc.returncode is not None:
            return False
        try:
            os.killpg(os.getpgid(proc.pid), signal_num)
        except ProcessLookupError:
            return False
        if signal_num != signal.SIGKILL:
            async def escalate():
                try:
                    # cancellable_wait, not wait_for: a cancel racing the
                    # exit must cancel the escalation, not be swallowed
                    await cancellable_wait(proc.wait(), timeout=10.0)
                except asyncio.TimeoutError:
                    try:
                        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            spawn(escalate(), name=f"kill-escalate-{container_id[-8:]}")
        return True

    async def state(self, container_id: str) -> Optional[ContainerHandle]:
        return self._handles.get(container_id)

    async def wait(self, container_id: str) -> int:
        proc = self._procs.get(container_id)
        if proc is None:
            handle = self._handles.get(container_id)
            return (handle.exit_code if handle
                    and handle.exit_code is not None else -1)
        code = await proc.wait()
        waiter = self._waiters.get(container_id)
        if waiter:
            # await the reap's FULL teardown (proxies/netns/overlay), not
            # just the exit: callers (lifecycle._supervise) mark the
            # container stopped when wait() returns, and a scale-down that
            # then deletes/re-mounts the image bundle must not race the
            # in-flight lazy umount (the coldstart_native teardown flake).
            # shield: the reap is shared by every wait() caller and owns
            # the teardown — one caller's cancel must not cancel it
            # (ProcessRuntime.wait precedent). gather (ASY003): our cancel
            # still reaches the caller. A CRASHED teardown is logged, not
            # raised: wait()'s contract is the exit code, and the primary
            # caller (lifecycle._supervise) does its container bookkeeping
            # + tpu.release unconditionally after wait() returns — an
            # exception here would leak the chip reservation forever.
            res = (await asyncio.gather(asyncio.shield(waiter),
                                        return_exceptions=True))[0]
            if (isinstance(res, BaseException)
                    and not isinstance(res, asyncio.CancelledError)):
                log.warning("container %s teardown failed after exit %s: %s",
                            container_id, code, res)
        return code

    def _nsenter(self, container_id: str) -> Optional[list[str]]:
        pid = self._container_pid(container_id)
        if pid is None:
            return None
        return ["nsenter", "-t", str(pid), "-m", "-u", "-i", "-p", "-n",
                "-r", "-w"]

    def fs_root(self, container_id: str):
        spec = self._specs.get(container_id)
        if spec is None:
            return None
        # the workspace dir rides into the container bind-mounted at its
        # host path, so the host path IS the container's working tree
        if spec.workdir not in ("", "/"):
            return spec.workdir
        return os.path.join(self.sandbox_dir(container_id), "rootfs")

    async def exec(self, container_id: str, cmd: list[str]) -> tuple[int, str]:
        enter = self._nsenter(container_id)
        if enter is None:
            return (-1, "container not running")
        proc = await asyncio.create_subprocess_exec(
            *enter, *cmd,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
        out, _ = await proc.communicate()
        return (proc.returncode or 0, out.decode(errors="replace"))

    async def exec_stream(self, container_id: str,
                          cmd: Optional[list[str]] = None):
        from .process import _PtySession
        enter = self._nsenter(container_id)
        if enter is None:
            raise RuntimeError("container not running")
        cmd = cmd or ["/bin/sh", "-i"]
        import pty as _pty
        master, slave = _pty.openpty()
        proc = await asyncio.create_subprocess_exec(
            *enter, *cmd, stdin=slave, stdout=slave, stderr=slave,
            preexec_fn=os.setsid, close_fds=True)
        os.close(slave)
        return _PtySession(master, proc)

    async def cleanup(self, container_id: str,
                      remove_sandbox: bool = True) -> None:
        await self._close_proxies(container_id)
        await asyncio.to_thread(self._teardown_net, container_id)
        await asyncio.to_thread(self._cleanup_mounts, container_id)
        self._procs.pop(container_id, None)
        self._handles.pop(container_id, None)
        self._specs.pop(container_id, None)
        self._waiters.pop(container_id, None)
        for t in self._log_tasks.pop(container_id, []):
            t.cancel()
        if remove_sandbox:
            shutil.rmtree(self.sandbox_dir(container_id), ignore_errors=True)

    def capabilities(self) -> set[str]:
        return {"exec", "exec_stream", "logs", "netns", "overlay", "devices"}
