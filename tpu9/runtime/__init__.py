from .base import ContainerHandle, Runtime, RuntimeState, ShellSession
from .native import NativeRuntime
from .process import ProcessRuntime
from .runc import RuncRuntime

__all__ = ["Runtime", "ContainerHandle", "RuntimeState", "ShellSession",
           "NativeRuntime", "ProcessRuntime", "RuncRuntime"]


def new_runtime(kind: str, **kw) -> Runtime:
    """Factory, analogue of the reference's ``runtime.New``
    (pkg/runtime/runtime.go:141)."""
    if kind == "process":
        return ProcessRuntime(**kw)
    if kind == "native":
        return NativeRuntime(**kw)
    if kind == "runc":
        return RuncRuntime(**kw)
    raise ValueError(f"unknown runtime {kind!r}")
