"""Worker-side client for the runner zygote (pre-warmed fork-server).

The ProcessRuntime uses this to start ``tpu9.runner.*`` containers as forks
of a process that already paid the jax/numpy/aiohttp imports — the JAX
cold-start tail (VERDICT r03 #4; reference analogue: CRIU
auto-checkpoint-after-ready, ``pkg/worker/criu.go:392``). One zygote per
runtime; the first container pays the zygote's own boot, every later one
forks in milliseconds.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import sys
from typing import Optional

log = logging.getLogger("tpu9.worker")


class ZygoteProc:
    """Duck-type of ``asyncio.subprocess.Process`` for zygote children —
    the ProcessRuntime's pump/reap/kill paths work unchanged."""

    def __init__(self, pid: int, exit_fut: "asyncio.Future[int]",
                 stdout: asyncio.StreamReader,
                 stderr: asyncio.StreamReader):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._exit_fut = exit_fut
        self.stdout = stdout
        self.stderr = stderr

    async def wait(self) -> int:
        self.returncode = await asyncio.shield(self._exit_fut)
        return self.returncode


class ZygoteClient:
    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._lock = asyncio.Lock()
        self._broken = False

    @property
    def available(self) -> bool:
        return not self._broken

    async def ensure_started(self, timeout_s: float = 90.0) -> bool:
        async with self._lock:
            if self._proc is not None and self._proc.returncode is None:
                return True
            if self._broken:
                return False
            os.makedirs(os.path.dirname(self.sock_path), exist_ok=True)
            repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            env = {k: v for k in ("PATH", "HOME", "LANG")
                   if (v := os.environ.get(k)) is not None}
            env["PYTHONPATH"] = repo_root
            env["PYTHONUNBUFFERED"] = "1"
            # the zygote itself must never dial an accelerator; children
            # re-pin jax.config from their own env post-fork
            env["JAX_PLATFORMS"] = "cpu"
            try:
                self._proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "tpu9.runner.zygote",
                    "--sock", self.sock_path, env=env,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL,
                    preexec_fn=os.setsid)
                line = await asyncio.wait_for(
                    self._proc.stdout.readline(), timeout_s)
                if b"ready" not in line:
                    raise RuntimeError(f"zygote said {line!r}")
            except (OSError, RuntimeError, asyncio.TimeoutError) as exc:
                log.warning("zygote unavailable (%s); falling back to "
                            "subprocess starts", exc)
                self._broken = True
                if self._proc is not None:
                    try:
                        self._proc.kill()
                    except ProcessLookupError:
                        pass
                    self._proc = None
                return False
            log.info("zygote warm at %s (pid %d)", self.sock_path,
                     self._proc.pid)
            return True

    async def spawn(self, env: dict, cwd: str, module: str,
                    argv: Optional[list] = None) -> ZygoteProc:
        """Fork a runner child; returns a Process-like handle whose
        stdout/stderr are live pipes."""
        stdout_r, stdout_w = os.pipe()
        stderr_r, stderr_w = os.pipe()
        try:
            # SCM_RIGHTS needs a raw socket (asyncio's TransportSocket hides
            # sendmsg): connect + send_fds blocking in a thread, then hand
            # the connected socket to asyncio for the reply stream
            payload = json.dumps({"env": env, "cwd": cwd, "module": module,
                                  "argv": argv or []}).encode() + b"\n"

            def handshake() -> socket.socket:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    s.settimeout(30.0)
                    s.connect(self.sock_path)
                    socket.send_fds(s, [payload], [stdout_w, stderr_w])
                    s.settimeout(None)
                except OSError:
                    s.close()
                    raise
                return s

            s = None
            writer = None
            s = await asyncio.to_thread(handshake)
            reader, writer = await asyncio.open_unix_connection(sock=s)
            line = await asyncio.wait_for(reader.readline(), 30.0)
            pid = json.loads(line)["pid"]
        except (OSError, ValueError, KeyError, asyncio.TimeoutError):
            # post-handshake failure: the zygote may have already forked a
            # child for this request. Closing the reply socket (never leak
            # its fd — advisor r04) is the zygote's signal to SIGKILL that
            # orphan before the caller falls back to exec and starts a
            # duplicate.
            if writer is not None:
                writer.close()
            elif s is not None:
                s.close()
            for fd in (stdout_r, stderr_r):
                os.close(fd)
            raise
        finally:
            os.close(stdout_w)
            os.close(stderr_w)

        loop = asyncio.get_running_loop()
        exit_fut: "asyncio.Future[int]" = loop.create_future()

        async def watch_exit() -> None:
            code = 1
            try:
                line = await reader.readline()
                if line:
                    code = int(json.loads(line).get("exit", 1))
            except (OSError, ValueError):
                pass
            finally:
                writer.close()
            if not exit_fut.done():
                exit_fut.set_result(code)

        watch_task = loop.create_task(watch_exit())

        async def stream_of(fd: int) -> asyncio.StreamReader:
            r = asyncio.StreamReader()
            await loop.connect_read_pipe(
                lambda: asyncio.StreamReaderProtocol(r),
                os.fdopen(fd, "rb", buffering=0))
            return r

        proc = ZygoteProc(pid, exit_fut, await stream_of(stdout_r),
                          await stream_of(stderr_r))
        # strong ref: the loop holds tasks weakly and a GC'd watcher would
        # leave exit_fut forever pending (container appears immortal)
        proc._watch_task = watch_task
        return proc

    async def stop(self) -> None:
        if self._proc is not None and self._proc.returncode is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), 9)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                await self._proc.wait()
            except Exception:       # noqa: BLE001
                pass
        self._proc = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
