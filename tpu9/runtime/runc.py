"""runc OCI runtime driver (production path on TPU VM workers).

Synthesizes an OCI ``config.json`` from a ContainerSpec — the analogue of the
reference's base spec + mutation flow (``pkg/runtime/base_runc_config.json``,
``pkg/worker/lifecycle.go:766`` specFromRequest) — and shells out to an
unmodified runc binary. TPU device access = bind /dev/accel* + /dev/vfio and
the libtpu.so path into the bundle (no CDI toolkit fork needed; see
SURVEY.md §2.9).

Gated: constructing it on a host without runc raises, and the worker falls
back to ProcessRuntime, so this module stays import-safe in the test image.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
from typing import Optional

from .base import ContainerHandle, ContainerSpec, Runtime, RuntimeState

_DEFAULT_CAPS = [
    "CAP_AUDIT_WRITE", "CAP_KILL", "CAP_NET_BIND_SERVICE", "CAP_CHOWN",
    "CAP_DAC_OVERRIDE", "CAP_FOWNER", "CAP_SETGID", "CAP_SETUID",
]

# same mask t9container's BPF inspects: clone with ANY namespace flag is
# an escape vector (Docker's default profile uses this exact constant)
_CLONE_NS_FLAGS = 0x7E020000


def _seccomp_profile(mode: str) -> Optional[dict]:
    """OCI seccomp section from the trace-generated allow-list (the same
    policy t9container compiles into BPF — native/t9_allowlist.json is the
    JSON twin the generator emits). ``deny`` keeps the legacy polarity;
    missing profile file → None (no seccomp, logged by the caller)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native",
        "t9_allowlist.json")
    try:
        with open(path) as f:
            lists = json.load(f)
    except (OSError, ValueError):
        return None
    common = [
        # clone3 → ENOSYS so libc falls back to clone (flags in memory,
        # uninspectable); clean clones allowed only with no ns flags
        {"names": ["clone3"], "action": "SCMP_ACT_ERRNO", "errnoRet": 38},
        {"names": ["clone"], "action": "SCMP_ACT_ALLOW",
         "args": [{"index": 0, "value": _CLONE_NS_FLAGS, "valueTwo": 0,
                   "op": "SCMP_CMP_MASKED_EQ"}]},
    ]
    if mode == "deny":
        return {"defaultAction": "SCMP_ACT_ALLOW",
                "architectures": ["SCMP_ARCH_X86_64"],
                "syscalls": common + [
                    {"names": sorted(set(lists["never_allow"])
                                     - {"clone3"}),
                     "action": "SCMP_ACT_ERRNO", "errnoRet": 1}]}
    allow = [n for n in lists["allow"] if n != "clone"]
    return {"defaultAction": "SCMP_ACT_ERRNO", "defaultErrnoRet": 1,
            "architectures": ["SCMP_ARCH_X86_64"],
            "syscalls": common + [
                {"names": allow, "action": "SCMP_ACT_ALLOW"}]}


def oci_spec_from(spec: ContainerSpec) -> dict:
    """Build the OCI runtime spec dict."""
    mounts = [
        {"destination": "/proc", "type": "proc", "source": "proc"},
        {"destination": "/dev", "type": "tmpfs", "source": "tmpfs",
         "options": ["nosuid", "strictatime", "mode=755", "size=65536k"]},
        {"destination": "/dev/shm", "type": "tmpfs", "source": "shm",
         "options": ["nosuid", "noexec", "nodev", "mode=1777",
                     "size=1073741824"]},
        {"destination": "/sys", "type": "sysfs", "source": "sysfs",
         "options": ["nosuid", "noexec", "nodev", "ro"]},
    ]
    for src, dst, ro in spec.mounts:
        opts = ["rbind"] + (["ro"] if ro else ["rw"])
        mounts.append({"destination": dst, "type": "bind", "source": src,
                       "options": opts})
    # TPU chips need both the bind mount AND a device-cgroup allow rule —
    # runc's default policy denies device access otherwise
    devices = []
    device_allows = []
    for dev in spec.devices:
        mounts.append({"destination": dev, "type": "bind", "source": dev,
                       "options": ["rbind", "rw"]})
        try:
            st = os.stat(dev)
            major, minor = os.major(st.st_rdev), os.minor(st.st_rdev)
            devices.append({"path": dev, "type": "c", "major": major,
                            "minor": minor, "fileMode": 0o666, "uid": 0,
                            "gid": 0})
            device_allows.append({"allow": True, "type": "c", "major": major,
                                  "minor": minor, "access": "rwm"})
        except OSError:
            device_allows.append({"allow": True, "access": "rwm"})

    resources: dict = {}
    if device_allows:
        resources["devices"] = device_allows
    if spec.cpu_millicores:
        resources["cpu"] = {"quota": spec.cpu_millicores * 100,
                            "period": 100000}
    if spec.memory_mb:
        resources["memory"] = {"limit": spec.memory_mb * 1024 * 1024}

    linux_extra: dict = {}
    if spec.seccomp_mode != "off":
        profile = _seccomp_profile(spec.seccomp_mode or "allow")
        if profile is not None:
            linux_extra["seccomp"] = profile

    return {
        "ociVersion": "1.0.2",
        "process": {
            "terminal": False,
            # the spec's identity drop is a CONTRACT (base.py: seccomp +
            # caps + no_new_privs apply on every runtime) — hardcoding
            # root here silently discarded it on the production path
            "user": {"uid": spec.run_as_uid, "gid": spec.run_as_gid},
            "args": spec.entrypoint,
            "env": [f"{k}={v}" for k, v in spec.env.items()],
            "cwd": spec.workdir or "/",
            "capabilities": {k: _DEFAULT_CAPS for k in
                             ("bounding", "effective", "permitted")},
            "noNewPrivileges": True,
        },
        # OCI-pulled snapshots chroot into <bundle>/rootfs; env snapshots
        # use the bundle dir itself. Decided by build-time metadata, not
        # directory layout — a user build step creating a rootfs/ dir must
        # not hijack the container root.
        "root": {"path": _root_path(spec.rootfs), "readonly": False},
        "hostname": spec.container_id,
        "mounts": mounts,
        "linux": {
            "resources": resources,
            "devices": devices,
            "namespaces": [{"type": t} for t in
                           ("pid", "ipc", "uts", "mount")],
            **linux_extra,
        },
    }


def _root_path(bundle: str) -> str:
    if not bundle:
        return "rootfs"
    meta = os.path.join(bundle, ".tpu9-env.json")
    try:
        with open(meta) as f:
            if json.load(f).get("kind") == "oci":
                return os.path.join(bundle, "rootfs")
    except (OSError, ValueError):
        pass
    return bundle


class RuncRuntime(Runtime):
    name = "runc"

    def __init__(self, base_dir: str = "/tmp/tpu9/bundles",
                 runc_path: str = "runc") -> None:
        if shutil.which(runc_path) is None:
            raise RuntimeError(f"runc binary not found: {runc_path}")
        self.base_dir = base_dir
        self.runc = runc_path
        self._handles: dict[str, ContainerHandle] = {}
        self._bg_tasks: set[asyncio.Task] = set()

    def bundle_dir(self, container_id: str) -> str:
        return os.path.join(self.base_dir, container_id)

    async def run(self, spec: ContainerSpec, log_cb=None) -> ContainerHandle:
        bundle = self.bundle_dir(spec.container_id)
        os.makedirs(bundle, exist_ok=True)
        with open(os.path.join(bundle, "config.json"), "w") as f:
            json.dump(oci_spec_from(spec), f)

        proc = await asyncio.create_subprocess_exec(
            self.runc, "run", "--bundle", bundle, spec.container_id,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE)
        handle = ContainerHandle(container_id=spec.container_id, pid=proc.pid,
                                 state=RuntimeState.RUNNING,
                                 meta={"proc": proc, "bundle": bundle})
        self._handles[spec.container_id] = handle

        async def pump(stream, name):
            while True:
                line = await stream.readline()
                if not line:
                    break
                if log_cb:
                    log_cb(line.decode(errors="replace").rstrip("\n"), name)

        async def reap():
            code = await proc.wait()
            handle.exit_code = code
            handle.state = (RuntimeState.STOPPED if code == 0
                            else RuntimeState.FAILED)

        # STRONG refs: the loop only weak-refs tasks — a GC'd reap would
        # leave the handle RUNNING forever (the lifecycle's early-crash
        # check and the OOM watcher both key on exit_code), and GC'd
        # pumps silently stop log streaming (same guard as native._bg)
        for t in (asyncio.create_task(pump(proc.stdout, "stdout")),
                  asyncio.create_task(pump(proc.stderr, "stderr")),
                  asyncio.create_task(reap())):
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
        return handle

    async def kill(self, container_id: str, signal_num: int = 15) -> bool:
        proc = await asyncio.create_subprocess_exec(
            self.runc, "kill", container_id, str(signal_num),
            stdout=asyncio.subprocess.DEVNULL, stderr=asyncio.subprocess.DEVNULL)
        return (await proc.wait()) == 0

    async def state(self, container_id: str) -> Optional[ContainerHandle]:
        return self._handles.get(container_id)

    async def wait(self, container_id: str) -> int:
        handle = self._handles.get(container_id)
        if handle is None:
            return -1
        proc = handle.meta.get("proc")
        if proc is None:
            return handle.exit_code if handle.exit_code is not None else -1
        return await proc.wait()

    async def exec(self, container_id: str, cmd: list[str]) -> tuple[int, str]:
        proc = await asyncio.create_subprocess_exec(
            self.runc, "exec", container_id, *cmd,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
        out, _ = await proc.communicate()
        return (proc.returncode or 0, out.decode(errors="replace"))

    async def exec_stream(self, container_id: str,
                          cmd: Optional[list[str]] = None):
        """Interactive shell via ``runc exec -t`` attached to a PTY (the
        `tpu9 shell` transport on the OCI path)."""
        import os as _os
        import pty as _pty

        from .process import _PtySession
        handle = self._handles.get(container_id)
        if handle is None:
            raise RuntimeError("container not running")
        cmd = cmd or ["/bin/sh", "-i"]
        master, slave = _pty.openpty()
        proc = await asyncio.create_subprocess_exec(
            self.runc, "exec", "-t", container_id, *cmd,
            stdin=slave, stdout=slave, stderr=slave,
            preexec_fn=_os.setsid, close_fds=True)
        _os.close(slave)
        return _PtySession(master, proc)

    def capabilities(self) -> set[str]:
        return {"exec", "exec_stream", "logs", "oci", "devices"}
