"""The tpu9 gateway (control plane).

Reference analogue: ``pkg/gateway/gateway.go`` — boots repositories,
scheduler, abstraction services; serves the SDK API + REST + invoke routes;
re-hydrates deployments on restart (InstanceController, instance.go:444);
drains before shutdown. One process, one port, embedded state server for
workers to join (the reference serves repos to workers over gRPC the same
way, gateway.go:353).

Route map:
  /api/v1/...                REST management API (auth: workspace token)
  /rpc/...                   SDK RPC (JSON bodies; auth: workspace token)
  /endpoint/{name}[/...]     invoke active deployment by name
  /health                    unauthenticated liveness
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import re
import time
from typing import Optional

from aiohttp import web

from ..utils.fsio import atomic_write_bytes
from ..abstractions.endpoint import EndpointService
from ..abstractions.function import FunctionService
from ..abstractions.image import ImageService
from ..abstractions.pod import PodService
from ..observability import EventBus, metrics
from ..scheduler.pool_health import PoolMonitor
from ..abstractions.primitives import (MapService, OutputService,
                                       PrimitiveError, QueueService,
                                       SignalService, VolumeFiles)
from ..abstractions.taskqueue import TaskQueueService
from ..images import ImageBuilder, ImageSpec
from ..backend import BackendDB
from ..config import AppConfig, env_no_egress
from ..repository import ContainerRepository, TaskRepository, WorkerRepository
from ..repository.keys import Keys
from ..scheduler import Scheduler
from ..statestore import MemoryStore, RemoteStore, StateServer, StateStore
from ..task import Dispatcher
from ..types import (Stub, StubConfig, StubType, TaskPolicy, Workspace,
                     new_id)

log = logging.getLogger("tpu9.gateway")


class Gateway:
    def __init__(self, cfg: AppConfig,
                 store: Optional[StateStore] = None,
                 backend: Optional[BackendDB] = None,
                 pools: Optional[dict] = None):
        self.cfg = cfg
        self.store = store or MemoryStore()
        if backend is None:
            # database.path accepts a postgresql:// DSN (HA control plane:
            # concurrent gateways over one Postgres) or a file path
            # (single-binary SQLite default)
            from ..backend.pg import open_backend
            backend = open_backend(cfg.database.path,
                                   secret_key=cfg.database.secret_key)
        self.backend = backend
        from ..scheduler.quota import QuotaService
        self.quota = QuotaService(self.store, self.backend)
        # agent-mode pools are self-hosted (machines reconcile against the
        # backend/store directly), so the gateway can always construct them
        self._pools_provided = pools is not None
        pools = dict(pools or {})
        from ..scheduler.pools import AgentMachinePool
        for p in cfg.pools:
            if p.mode == "agent" and p.name not in pools:
                pools[p.name] = AgentMachinePool(p, self.backend, self.store)
        self.scheduler = Scheduler(self.store, cfg.scheduler,
                                   pools=pools, quota=self.quota)
        self.workers = WorkerRepository(self.store, cfg.worker.keepalive_ttl_s)
        self.containers = ContainerRepository(self.store)
        self.tasks = TaskRepository(self.store)
        from ..abstractions.common.tokens import RunnerTokenCache
        self.runner_tokens = RunnerTokenCache(self.backend)
        # containers read this to reach us; filled once the port is bound
        self.runner_env: dict[str, str] = {}
        # fleet inference router (ISSUE 2): KV-affinity routing, per-tenant
        # fair queuing, SLO-aware shedding on the invoke paths
        self.fleet_router = None
        if cfg.router.enabled:
            from ..router import FleetRouter
            self.fleet_router = FleetRouter(cfg.router, self.store,
                                            self.containers,
                                            backend=self.backend)
        self.endpoints = EndpointService(self.backend, self.scheduler,
                                         self.containers,
                                         runner_env=self.runner_env,
                                         runner_tokens=self.runner_tokens)
        self.endpoints.fleet_router = self.fleet_router
        # request survivability (ISSUE 15): idempotency journal for
        # client-supplied X-Tpu9-Request-Id retries — a client retry of
        # an in-flight/completed request attaches to the journal instead
        # of double-executing
        from .survival import RequestJournal
        self.journal = RequestJournal(self.store,
                                      ttl_s=cfg.router.journal_ttl_s,
                                      body_cap=cfg.router.journal_body_cap)
        self.dispatcher = Dispatcher(self.store, self.backend)

        async def _container_alive(container_id: str) -> bool:
            return await self.containers.get_state(container_id) is not None

        self.dispatcher.container_alive = _container_alive
        self.taskqueues = TaskQueueService(self.backend, self.scheduler,
                                           self.containers, self.dispatcher,
                                           runner_env=self.runner_env,
                                           runner_tokens=self.runner_tokens)
        self.functions = FunctionService(self.backend, self.scheduler,
                                         self.containers, self.dispatcher,
                                         runner_env=self.runner_env,
                                         runner_tokens=self.runner_tokens)
        self.images = ImageService(
            self.backend,
            ImageBuilder(cfg.image.registry_dir,
                         network_ok=not env_no_egress()),
            scheduler=self.scheduler,
            runner_env=self.runner_env,
            runner_tokens=self.runner_tokens,
            build_mode=cfg.image.build_mode,
            build_timeout_s=cfg.image.build_timeout_s,
            build_cpu_millicores=cfg.image.build_cpu_millicores,
            build_memory_mb=cfg.image.build_memory_mb)
        self.pods = PodService(self.backend, self.scheduler, self.containers,
                               self.store, runner_env=self.runner_env,
                               runner_tokens=self.runner_tokens)
        from ..abstractions.disk import DiskService
        self.disks = DiskService(self.backend, self.store)
        # every request-building service decorates disk mounts with
        # snapshot ids + placement affinity
        self.pods.disks = self.disks
        self.endpoints.disks = self.disks
        self.taskqueues.disks = self.disks
        self.functions.disks = self.disks
        from ..abstractions.bot import BotService
        self.bots = BotService(self.backend, self.scheduler, self.containers,
                               self.dispatcher, self.store,
                               runner_env=self.runner_env,
                               runner_tokens=self.runner_tokens)
        self.bots.disks = self.disks
        self.maps = MapService(self.store)
        self.queues = QueueService(self.store)
        self.signals = SignalService(self.store)
        self.outputs = OutputService(self.backend, cfg.storage.local_root)
        from ..storage import make_store
        self.volume_files = VolumeFiles(self.backend, cfg.storage.local_root,
                                        store=make_store(cfg.storage))
        # (ws, name) -> (listing fingerprint, manifest json) for CacheFS
        # volume mounts — re-chunking a stable multi-GB volume per mount
        # would dwarf the mount itself
        self._volume_manifest_cache: dict[tuple, tuple[str, str]] = {}
        self._volume_manifest_builds: dict[tuple, asyncio.Task] = {}
        self.events = EventBus(self.store, sink_url=cfg.monitoring.events_http_url
                               if cfg.monitoring.events_sink == "http" else "",
                               cluster=cfg.cluster_name)
        from ..observability import UsageService
        self.usage = UsageService(self.store, self.backend)
        # decision ledger caps (ISSUE 19): re-bound the module singleton
        # from config before any plane records into it
        from ..observability.decisions import ledger as decision_ledger
        decision_ledger.configure(
            capacity=cfg.slo.decisions_capacity,
            max_requests=cfg.slo.decisions_max_requests,
            per_request=cfg.slo.decisions_per_request,
            idle_ttl_s=cfg.slo.decisions_idle_ttl_s)
        # fleet SLO / timeline / goodput layer (ISSUE 12): bounded
        # time-series store + burn-rate evaluator + per-tenant goodput
        # accounting behind /api/v1/{timeline,slo} and `tpu9 top`
        self.fleetobs = None
        # scale-out plane (ISSUE 17): the gateway-side multicast-tree
        # coordinator — fed by the observer's cache-plane/heartbeat
        # cadences, publishing the tree plan joining workers read
        self.scaleout = None
        if cfg.slo.enabled:
            from ..scaleout import scaleout_on
            if scaleout_on(cfg.scaleout):
                from ..scaleout.coordinator import ScaleoutCoordinator
                self.scaleout = ScaleoutCoordinator(cfg.scaleout)
            from .fleetobs import FleetObserver
            self.fleetobs = FleetObserver(cfg.slo, self.store,
                                          fleet_router=self.fleet_router,
                                          scaleout=self.scaleout)
        self.pool_monitor = PoolMonitor(
            self.store, pools,
            {p.name: p for p in cfg.pools},
            quota=self.quota) if (self._pools_provided or pools) else None
        self.extra_services: dict[str, object] = {}
        self.state_server: Optional[StateServer] = None
        self.relay = None              # Optional[RelayServer]
        self.dialer = None             # Optional[Dialer]
        self.otlp = None               # Optional[OtlpExporter]
        self._proxy_session = None     # shared pod-proxy ClientSession
        # verified (proc_id → container_id) pairings for sandbox output
        # polls: one worker round-trip per proc, then bus reads only
        self._sbx_proc_owner: dict[str, str] = {}
        self._runner: Optional[web.AppRunner] = None
        self._shutting_down = asyncio.Event()
        self.port = cfg.gateway.http_port
        self.app = self._build_app()

    # ------------------------------------------------------------------

    def _build_app(self) -> web.Application:
        app = web.Application(middlewares=[self._quota_middleware,
                                           self._auth_middleware],
                              client_max_size=512 * 1024 * 1024)
        r = app.router
        r.add_get("/health", self._health)
        # SDK RPC
        r.add_post("/rpc/auth/check", self._rpc_auth_check)
        r.add_post("/rpc/stub/get-or-create", self._rpc_get_or_create_stub)
        r.add_post("/rpc/object/put", self._rpc_put_object)
        r.add_get("/rpc/object/{object_id}", self._rpc_get_object)
        r.add_post("/rpc/deploy", self._rpc_deploy)
        # tasks / queues / functions
        r.add_post("/rpc/taskqueue/put", self._rpc_tq_put)
        r.add_post("/rpc/taskqueue/pop", self._rpc_tq_pop)
        r.add_get("/rpc/taskqueue/status/{stub_id}", self._rpc_tq_status)
        r.add_post("/rpc/function/invoke", self._rpc_fn_invoke)
        r.add_post("/rpc/schedule/register", self._rpc_schedule_register)
        r.add_get("/rpc/task/{task_id}", self._rpc_task_get)
        r.add_get("/rpc/task/{task_id}/result", self._rpc_task_result)
        r.add_post("/rpc/task/{task_id}/claim", self._rpc_task_claim)
        r.add_post("/rpc/task/{task_id}/complete", self._rpc_task_complete)
        r.add_post("/rpc/task/{task_id}/cancel", self._rpc_task_cancel)
        r.add_post("/rpc/llm/pressure", self._rpc_llm_pressure)
        r.add_post("/rpc/llm/postmortem", self._rpc_llm_postmortem)
        # bot (petri-net orchestration)
        r.add_post("/rpc/bot/session", self._rpc_bot_session_create)
        r.add_get("/rpc/bot/{stub_id}/sessions", self._rpc_bot_sessions)
        r.add_delete("/rpc/bot/{stub_id}/session/{session_id}",
                     self._rpc_bot_session_delete)
        r.add_post("/rpc/bot/{stub_id}/session/{session_id}/push",
                   self._rpc_bot_push)
        r.add_post("/rpc/bot/{stub_id}/session/{session_id}/pop",
                   self._rpc_bot_pop)
        r.add_get("/rpc/bot/{stub_id}/session/{session_id}/state",
                  self._rpc_bot_state)
        r.add_get("/rpc/bot/{stub_id}/session/{session_id}/events",
                  self._rpc_bot_events)
        # pods / sandboxes
        r.add_post("/rpc/pod/create", self._rpc_pod_create)
        r.add_get("/rpc/pod/{container_id}/status", self._rpc_pod_status)
        r.add_post("/rpc/pod/{container_id}/exec", self._rpc_pod_exec)
        # sandbox depth: process manager / fs API / snapshots
        # (reference sdk sandbox.py:137,376,916)
        r.add_post("/rpc/pod/{container_id}/proc", self._rpc_sbx_spawn)
        r.add_get("/rpc/pod/{container_id}/proc", self._rpc_sbx_ps)
        r.add_get("/rpc/pod/{container_id}/proc/{proc_id}",
                  self._rpc_sbx_status)
        r.add_post("/rpc/pod/{container_id}/proc/{proc_id}/stdin",
                   self._rpc_sbx_stdin)
        r.add_post("/rpc/pod/{container_id}/proc/{proc_id}/kill",
                   self._rpc_sbx_kill)
        r.add_get("/rpc/pod/{container_id}/proc/{proc_id}/out",
                  self._rpc_sbx_out)
        r.add_post("/rpc/pod/{container_id}/fs", self._rpc_sbx_fs)
        r.add_post("/rpc/pod/{container_id}/snapshot",
                   self._rpc_sbx_snapshot)
        r.add_post("/rpc/pod/{container_id}/criu-checkpoint",
                   self._rpc_criu_checkpoint)
        r.add_get("/rpc/pod/snapshots", self._rpc_sbx_snapshots)
        r.add_route("*", "/pod/{container_id}/{tail:.*}", self._pod_proxy)
        # primitives
        r.add_post("/rpc/map/{name}", self._rpc_map)
        r.add_post("/rpc/queue/{name}", self._rpc_queue)
        r.add_post("/rpc/signal/{name}", self._rpc_signal)
        r.add_post("/rpc/output/save", self._rpc_output_save)
        r.add_get("/rpc/output/{output_id}", self._rpc_output_get)
        # durable disks
        r.add_get("/api/v1/disk", self._list_disks)
        r.add_post("/api/v1/disk/{name}/snapshot", self._disk_snapshot)
        r.add_delete("/api/v1/disk/{name}", self._disk_delete)
        # worker-token disk internals (manifest store/fetch + chunk sink
        # ride the image chunk registry)
        r.add_post("/rpc/internal/disk/{workspace_id}/{name}/manifest/"
                   "{snapshot_id}", self._internal_disk_manifest_put)
        r.add_get("/rpc/internal/disk/manifest/{snapshot_id}",
                  self._internal_disk_manifest_get)
        r.add_post("/rpc/internal/sbxsnap/{workspace_id}/{container_id}/"
                   "{snapshot_id}", self._internal_sbxsnap_put)
        r.add_get("/rpc/internal/sbxsnap/manifest/{snapshot_id}",
                  self._internal_sbxsnap_get)
        # container checkpoints (readiness-trigger restore fast path):
        # workers record the row, stream chunks into the distributed cache,
        # then land the manifest here; the scheduler's checkpoint_lookup
        # only hands out rows the status endpoint marked 'available'
        r.add_post("/rpc/internal/ckpt/{workspace_id}/{stub_id}/"
                   "{container_id}", self._internal_ckpt_record)
        r.add_post("/rpc/internal/ckpt/status/{checkpoint_id}",
                   self._internal_ckpt_status)
        r.add_post("/rpc/internal/ckpt/manifest/{checkpoint_id}",
                   self._internal_ckpt_manifest_put)
        r.add_get("/rpc/internal/ckpt/manifest/{checkpoint_id}",
                  self._internal_ckpt_manifest_get)
        r.add_get("/api/v1/volume", self._list_volumes)
        r.add_post("/api/v1/volume/{name}", self._create_volume)
        r.add_delete("/api/v1/volume/{name}", self._delete_volume)
        r.add_get("/rpc/volume/{name}/files", self._volume_list)
        r.add_put("/rpc/volume/{name}/files/{path:.+}", self._volume_put)
        r.add_get("/rpc/volume/{name}/files/{path:.+}", self._volume_get)
        r.add_delete("/rpc/volume/{name}/files/{path:.+}", self._volume_delete)
        # multipart volume transfer (reference sdk multipart.py)
        # worker-token volume reads for cross-host sync (repo-over-gRPC
        # semantics: workers act on behalf of any workspace)
        r.add_get("/rpc/internal/volume/{workspace_id}/{name}/manifest",
                  self._internal_volume_manifest)
        r.add_get("/rpc/internal/volume/{workspace_id}/{name}/files",
                  self._internal_volume_list)
        r.add_get("/rpc/internal/volume/{workspace_id}/{name}/files/{path:.+}",
                  self._internal_volume_get)
        r.add_put("/rpc/internal/volume/{workspace_id}/{name}/files/{path:.+}",
                  self._internal_volume_put)
        r.add_post("/rpc/volume/{name}/multipart/initiate/{path:.+}",
                   self._volume_mp_initiate)
        r.add_put("/rpc/volume/{name}/multipart/{upload_id}/{index}",
                  self._volume_mp_part)
        r.add_post("/rpc/volume/{name}/multipart/{upload_id}/complete",
                   self._volume_mp_complete)
        r.add_delete("/rpc/volume/{name}/multipart/{upload_id}",
                     self._volume_mp_abort)
        # images
        r.add_post("/rpc/image/verify", self._rpc_image_verify)
        r.add_post("/rpc/image/build", self._rpc_image_build)
        r.add_get("/rpc/image/status/{image_id}", self._rpc_image_status)
        r.add_get("/rpc/image/manifest/{image_id}", self._rpc_image_manifest)
        r.add_get("/rpc/image/chunk/{digest}", self._rpc_image_chunk)
        # build-runner upload API (runner/worker tokens)
        r.add_post("/rpc/image/chunk/{digest}", self._rpc_image_chunk_put)
        r.add_post("/rpc/image/manifest/{image_id}",
                   self._rpc_image_manifest_put)
        r.add_post("/rpc/image/complete/{image_id}",
                   self._rpc_image_complete)
        # REST v1 (management)
        r.add_get("/api/v1/deployment", self._list_deployments)
        r.add_delete("/api/v1/deployment/{id}", self._delete_deployment)
        r.add_get("/api/v1/container", self._list_containers)
        r.add_post("/api/v1/container/{id}/stop", self._stop_container)
        r.add_get("/api/v1/container/{id}/logs", self._container_logs)
        r.add_get("/api/v1/container/{id}/shell", self._container_shell)
        r.add_get("/api/v1/task", self._list_tasks)
        r.add_get("/api/v1/worker", self._list_workers)
        r.add_get("/api/v1/stub", self._list_stubs)
        r.add_get("/api/v1/secret", self._list_secrets)
        r.add_post("/api/v1/secret", self._upsert_secret)
        r.add_delete("/api/v1/secret/{name}", self._delete_secret)
        r.add_get("/api/v1/scheduler/stats", self._scheduler_stats)
        r.add_get("/api/v1/metrics", self._metrics)
        r.add_get("/api/v1/usage", self._usage_report)
        r.add_get("/api/v1/timeline", self._timeline)
        r.add_get("/api/v1/slo", self._slo)
        r.add_get("/api/v1/traces", self._traces)
        r.add_get("/api/v1/decisions", self._decisions)
        r.add_get("/api/v1/coldstart", self._coldstart)
        r.add_get("/api/v1/scaleout", self._scaleout)
        r.add_get("/api/v1/postmortem", self._postmortem)
        # engine flight recorder + on-demand TPU profiling (ISSUE 8)
        r.add_get("/api/v1/flight", self._flight)
        r.add_post("/api/v1/profile", self._profile)
        # per-workspace concurrency quotas (reference concurrencylimit.go);
        # reads are self-service, writes are operator-only
        r.add_get("/api/v1/concurrency-limit", self._get_concurrency_limit)
        r.add_post("/api/v1/concurrency-limit/{workspace_id}",
                   self._set_concurrency_limit)
        r.add_delete("/api/v1/concurrency-limit/{workspace_id}",
                     self._delete_concurrency_limit)
        # apps: deployment grouping (reference /api/v1/app group)
        r.add_get("/api/v1/app", self._list_apps)
        r.add_delete("/api/v1/app/{app_id}", self._delete_app)
        r.add_get("/api/v1/events", self._events)
        r.add_get("/api/v1/pools", self._pools)
        # workspaces (reference /api/v1/workspace group)
        r.add_post("/api/v1/workspace", self._workspace_create)
        r.add_post("/api/v1/workspace/{workspace_id}/token",
                   self._workspace_token)
        # tokens: self-service CRUD (reference /api/v1/token group)
        r.add_get("/api/v1/token", self._token_list)
        r.add_post("/api/v1/token", self._token_create)
        r.add_delete("/api/v1/token/{token_id}", self._token_revoke)
        # machines: BYOC agent fleet (reference pkg/agent + /api/v1/machine)
        r.add_post("/api/v1/machine", self._machine_create)
        r.add_get("/api/v1/machine", self._machine_list)
        r.add_delete("/api/v1/machine/{machine_id}", self._machine_delete)
        r.add_post("/api/v1/machine/join", self._machine_join)
        r.add_get("/api/v1/machine/{machine_id}/desired",
                  self._machine_desired)
        r.add_post("/api/v1/machine/{machine_id}/heartbeat",
                   self._machine_heartbeat)
        r.add_post("/api/v1/machine/{machine_id}/release",
                   self._machine_release)
        # worker-log relay through the agent (reference log_writer.go):
        # agents POST batches; operators read the tail
        r.add_post("/api/v1/machine/{machine_id}/logs",
                   self._machine_logs_push)
        r.add_get("/api/v1/machine/{machine_id}/logs",
                  self._machine_logs_get)
        # invoke
        r.add_route("*", "/endpoint/{name}", self._invoke)
        r.add_route("*", "/endpoint/{name}/{tail:.*}", self._invoke)
        # subdomain routing (reference middleware/subdomain.go:30): a request
        # whose Host is <subdomain>.<anything> hits its deployment directly.
        # Registered last so explicit routes win.
        r.add_route("*", "/{tail:.*}", self._subdomain_invoke)
        return app

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "Gateway":
        if isinstance(self.store, RemoteStore):
            await self.store.connect()
        elif isinstance(self.store, MemoryStore) and self.cfg.gateway.state_port:
            # expose the embedded store to out-of-process workers
            # (state_port 0 disables; -1 means "any free port")
            port = max(self.cfg.gateway.state_port, 0)
            self.state_server = await StateServer(
                store=self.store, host=self.cfg.gateway.host, port=port,
                auth_token=self.cfg.database.state_auth_token).start()
        if self.cfg.gateway.relay_port:
            adv = self.cfg.gateway.advertise_host or self.cfg.gateway.host
            if adv in ("", "0.0.0.0", "::"):
                # a wildcard bind is not dialable by workers; external_url's
                # host is the address they actually reach us at
                ext = self.cfg.gateway.external_url
                adv = ext.split("://", 1)[-1].split("/", 1)[0] \
                    .rsplit(":", 1)[0] if ext else ""
            if adv:
                from ..network import Dialer, RelayServer
                # bind where the gateway itself binds: loopback-only dev
                # setups must not grow a world-reachable port
                self.relay = await RelayServer(
                    host=self.cfg.gateway.host or "0.0.0.0",
                    port=max(self.cfg.gateway.relay_port, 0)).start()
                self.dialer = await Dialer(self.store, self.relay,
                                           advertise_host=adv).start()
                # every container-proxy surface routes through the dialer
                self.endpoints.dialer = self.dialer
            else:
                log.warning(
                    "relay disabled: gateway binds %r and neither "
                    "gateway.advertise_host nor gateway.external_url is set "
                    "— workers could never dial back",
                    self.cfg.gateway.host)
        if self.cfg.monitoring.otlp_endpoint:
            from ..observability.otel import OtlpExporter
            self.otlp = await OtlpExporter(
                self.cfg.monitoring.otlp_endpoint,
                service=f"tpu9-gateway-{self.cfg.cluster_name}",
                interval_s=self.cfg.monitoring.otlp_interval_s).start()
        await self.scheduler.start()
        await self.dispatcher.start()
        await self.functions.start()
        await self.usage.start()
        if self.fleetobs is not None:
            await self.fleetobs.start()
        if self.pool_monitor is not None:
            await self.pool_monitor.start()
        # shutdown grace: long-polls exit instantly via _bounded_longpoll
        # (the _shutting_down event), so this bound only backstops
        # genuinely slow handlers — 15s keeps normal invokes intact while
        # a stop never waits aiohttp's default 60s
        self._runner = web.AppRunner(self.app, shutdown_timeout=15.0)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.cfg.gateway.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        self.runner_env["TPU9_GATEWAY_URL"] = (
            self.cfg.gateway.external_url
            or f"http://{self.cfg.gateway.host}:{self.port}")
        await self._ensure_default_workspace()
        await self._rehydrate_deployments()
        log.info("gateway on %s:%d", self.cfg.gateway.host, self.port)
        return self

    async def _bounded_longpoll(self, coro):
        """Race a long-poll against gateway shutdown: a stop releases every
        waiting pop/result request immediately with its empty answer
        (clients retry after reconnect) instead of holding the HTTP drain
        for the poll's full timeout."""
        wait = asyncio.ensure_future(coro)
        stop = asyncio.ensure_future(self._shutting_down.wait())
        try:
            done, _ = await asyncio.wait({wait, stop},
                                         return_when=asyncio.FIRST_COMPLETED)
            if wait in done:
                return wait.result()
            return None
        finally:
            # runs on BOTH exits AND on handler cancellation (client
            # disconnect): an orphaned pop would otherwise keep running —
            # possibly dequeuing a task whose response nobody receives —
            # and the stray Event waiter would accumulate per request
            for t in (wait, stop):
                if not t.done():
                    t.cancel()
            # gather, not `except BaseException: pass` (ASY003): absorbs
            # the cancelled poll's CancelledError but re-raises if the
            # handler itself is cancelled while draining
            await asyncio.gather(wait, return_exceptions=True)

    async def stop(self) -> None:
        self._shutting_down.set()       # FIRST: releases every long-poll
        if self.pool_monitor is not None:
            await self.pool_monitor.stop()
        if self.fleet_router is not None:
            await self.fleet_router.stop()
        await self.endpoints.shutdown()
        await self.taskqueues.shutdown()
        await self.functions.stop()
        await self.dispatcher.stop()
        await self.scheduler.stop()
        if self.fleetobs is not None:
            await self.fleetobs.stop()
        await self.usage.stop()
        if self.otlp is not None:
            await self.otlp.stop()
        if self._proxy_session is not None and not self._proxy_session.closed:
            await self._proxy_session.close()
        if self.dialer is not None:
            await self.dialer.stop()
        if self.relay is not None:
            await self.relay.stop()
        if self._runner:
            await self._runner.cleanup()
        if self.state_server:
            await self.state_server.stop()
        await self.backend.close()

    async def _ensure_default_workspace(self) -> None:
        """Dev bootstrap: a default workspace + user/worker tokens, printed
        once (the reference seeds via migrations/CLI config flow)."""
        ws = await self.backend.get_workspace_by_name("default")
        if ws is None:
            ws = await self.backend.create_workspace("default")
            tok = await self.backend.create_token(ws.workspace_id)
            self.default_token = tok.key
            log.info("created default workspace; token=%s", tok.key)
        else:
            toks = await self.backend.list_tokens(ws.workspace_id)
            # ACTIVE only: a revoked key must not be resurrected as the
            # printed default (or, worse, handed to every joining machine)
            user = [t for t in toks
                    if t.token_type == "workspace" and t.active]
            self.default_token = user[0].key if user else ""
        worker_toks = [t for t in await self.backend.list_tokens(ws.workspace_id)
                       if t.token_type == "worker" and t.active]
        if worker_toks:
            self.worker_token = worker_toks[0].key
        else:
            wt = await self.backend.create_token(ws.workspace_id,
                                                 token_type="worker")
            self.worker_token = wt.key
        self.default_workspace = ws

    async def _rehydrate_deployments(self) -> None:
        """Re-create autoscaled instances for active deployments after a
        restart (instance.go:444-530)."""
        for dep in await self.backend.list_active_deployments():
            stub = await self.backend.get_stub(dep.stub_id)
            if stub is None:
                continue
            if stub.stub_type in (StubType.ENDPOINT.value,
                                  StubType.ASGI.value,
                                  StubType.REALTIME.value):
                await self.endpoints.get_or_create_instance(stub)
            elif stub.stub_type == StubType.TASK_QUEUE.value:
                await self.taskqueues.get_or_create_instance(stub)

    @web.middleware
    async def _quota_middleware(self, request: web.Request, handler):
        """Concurrency-quota rejections surface as 429 wherever the request
        originated (pod create, task submit, deploy scale-up...)."""
        from ..scheduler.quota import QuotaExceeded
        try:
            return await handler(request)
        except QuotaExceeded as exc:
            return web.json_response({"error": str(exc)}, status=429)

    # -- auth ----------------------------------------------------------------

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        if request.path in ("/health",):
            return await handler(request)
        token = ""
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            token = auth[len("Bearer "):]
        tok = await self.backend.authorize_token(token) if token else None
        if tok is None:
            # explicit allowlist of maybe-public surfaces: named invoke
            # routes and the subdomain catch-all (which 404s unknown hosts);
            # everything else is auth-required by default (fail closed)
            route_handler = getattr(request.match_info, "handler", None)
            # bound-method comparison needs ==, not `is` (fresh object per
            # attribute access)
            if (request.path.startswith("/endpoint/")
                    or request.path == "/api/v1/machine/join"
                    or route_handler == self._subdomain_invoke):
                # machine join authenticates with its one-time join token
                # in the body, not a workspace bearer token
                request["workspace"] = None
                return await handler(request)
            return web.json_response({"error": "unauthorized"}, status=401)
        request["workspace"] = await self.backend.get_workspace(tok.workspace_id)
        # worker tokens may read cross-workspace artifacts (objects, chunks)
        # the way the reference serves repos to workers over gRPC
        request["is_worker"] = tok.token_type == "worker"
        request["token_type"] = tok.token_type
        return await handler(request)

    def _ws(self, request: web.Request) -> Workspace:
        ws = request.get("workspace")
        if ws is None:
            raise web.HTTPUnauthorized(
                text=json.dumps({"error": "unauthorized"}),
                content_type="application/json")
        return ws

    # -- handlers: health/misc ----------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "ok": True,
            "backlog": await self.scheduler.backlog_depth(),
            "workers": len(await self.workers.list()),
        })

    async def _scheduler_stats(self, request: web.Request) -> web.Response:
        self._require_operator(request)   # fleet internals: operator-only
        return web.json_response(self.scheduler.stats)

    async def _usage_report(self, request: web.Request) -> web.Response:
        """Per-workspace metered usage: container-seconds, chip-seconds,
        requests (usage_openmeter.go:18 analogue, hourly buckets)."""
        ws = self._ws(request)
        hours = min(int(self._q_float(request, "hours", 24)), 24 * 31)
        return web.json_response(
            await self.usage.query(ws.workspace_id, hours=hours))

    async def _traces(self, request: web.Request) -> web.Response:
        """Merged fleet traces: this process's span ring + rings workers
        ship on their heartbeat (common/trace.go:12 analogue). Workspace-
        scoped: spans are stamped with the workspace they served, and a
        caller only sees its own."""
        ws = self._ws(request)
        from ..observability import tracer
        trace_id = request.query.get("trace_id", "")
        since = self._q_float(request, "since", 0)
        limit = min(int(self._q_float(request, "limit", 1000)), 5000)

        def visible(sp: dict) -> bool:
            if trace_id and sp.get("traceId") != trace_id:
                return False
            if sp.get("endTimeUnixNano", 0) / 1e9 < since:
                return False
            return (sp.get("attributes", {}).get("workspace_id")
                    == ws.workspace_id)

        seen: set[str] = set()
        spans = []
        for sp in tracer.export(trace_id=trace_id, since=since, limit=limit):
            if visible(sp) and sp.get("spanId") not in seen:
                seen.add(sp.get("spanId", ""))
                spans.append(sp)
        # worker rings (cold-start spans) + runner rings (engine spans
        # shipped on the pressure heartbeat, ISSUE 8) — one merged,
        # workspace-scoped timeline per trace id
        for pattern in ("worker:traces:*", "runner:traces:*"):
            for key in await self.store.keys(pattern):
                raw = await self.store.get(key)
                if not raw:
                    continue
                try:
                    for sp in json.loads(raw):
                        # dedup by spanId: in-process topologies share one
                        # ring, so every worker ships the same spans
                        if visible(sp) and sp.get("spanId") not in seen:
                            seen.add(sp.get("spanId", ""))
                            spans.append(sp)
                except (ValueError, TypeError):
                    continue
        spans.sort(key=lambda s: s.get("startTimeUnixNano", 0))
        return web.json_response({"spans": spans[:limit]})

    async def _decisions(self, request: web.Request) -> web.Response:
        """Merged fleet decision ledger (ISSUE 19): this process's ring
        (admission / placement / failover / autoscaler records) + the
        rings LLM runners ship on the pressure heartbeat (migration
        adopt/drain evidence). Workspace-scoped like /api/v1/traces —
        records are stamped with the workspace they served and a caller
        only sees its own; records with no workspace stamp (autoscaler
        ticks, tree replans) are fleet history, operator-only."""
        ws = self._ws(request)
        operator = self._is_operator(request)
        from ..observability.decisions import ledger as decision_ledger
        request_id = request.query.get("request_id", "")
        plane = request.query.get("plane", "")
        since = self._q_float(request, "since", 0.0)
        limit = min(int(self._q_float(request, "limit", 500)), 5000)

        def visible(rec: dict) -> bool:
            rws = rec.get("workspace_id", "")
            return operator or rws == ws.workspace_id

        records = [r for r in decision_ledger.query(
            request_id=request_id, plane=plane, since=since, limit=limit)
            if visible(r)]
        # dedup by (container_id, seq): each process numbers its own
        # records, and only runner-shipped ones carry a container stamp
        seen = {(r.get("container_id", ""), r.get("seq")) for r in records}
        for key in await self.store.keys("runner:decisions:*"):
            raw = await self.store.get(key)
            if not raw:
                continue
            try:
                ring = json.loads(raw)
            except (ValueError, TypeError):
                continue
            for rec in ring:
                if not isinstance(rec, dict) or not visible(rec):
                    continue
                if request_id and rec.get("request_id") != request_id:
                    continue
                if plane and rec.get("plane") != plane:
                    continue
                if rec.get("ts", 0.0) < since:
                    continue
                k = (rec.get("container_id", ""), rec.get("seq"))
                if k in seen:
                    continue
                seen.add(k)
                records.append(rec)
        records.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", 0)))
        return web.json_response({"records": records[:limit]})

    async def _coldstart(self, request: web.Request) -> web.Response:
        """Per-replica cold-start decomposition records (ISSUE 13):
        worker-half restore records (coldstart:<container_id> keys shipped
        on the worker heartbeat — plan/fetch/put intervals, bytes by cache
        tier, hedge outcomes) merged with the runner-half coldstart_*
        pressure extras (load/compile_ahead/bind/warmup/ready). Workspace-
        scoped like /api/v1/traces; ?container_id= pins one replica,
        ?stub_id= filters a deployment. This record is the artifact the
        ROADMAP item-3 `--phase scaleout` bench gates on."""
        ws = self._ws(request)
        operator = self._is_operator(request)
        want_cid = request.query.get("container_id", "")
        want_stub = request.query.get("stub_id", "")
        out = await self._coldstart_records(ws, operator, want_cid,
                                            want_stub)
        return web.json_response({"replicas": out})

    async def _coldstart_records(self, ws, operator: bool, want_cid: str,
                                 want_stub: str) -> dict:
        """Workspace-scoped merged coldstart records, shared by
        /api/v1/coldstart and /api/v1/scaleout (ISSUE 17)."""
        from ..observability.coldstart import merge_record
        # both key families are suffixed by container id — a pinned query
        # reads exactly two keys instead of scanning the fleet
        pressure_keys = [f"llm:pressure:{want_cid}"] if want_cid \
            else await self.store.keys("llm:pressure:*")
        coldstart_keys = [f"coldstart:{want_cid}"] if want_cid \
            else await self.store.keys("coldstart:*")
        # runner halves, keyed by container: the same pressure hashes
        # /api/v1/metrics "engines" reads
        runner_halves: dict[str, dict] = {}
        for key in pressure_keys:
            snap = await self.store.hgetall(key)
            if snap:
                runner_halves[key.rsplit(":", 1)[-1]] = snap
        replicas: dict[str, dict] = {}
        for key in coldstart_keys:
            raw = await self.store.get(key)
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except (ValueError, TypeError):
                continue
            cid = rec.get("container_id", key.rsplit(":", 1)[-1])
            replicas[cid] = rec
        # runner-only replicas (no streamed restore — cold boot or warm
        # pool on a fresh node) still get a record from their heartbeat
        for cid in runner_halves:
            replicas.setdefault(cid, {"container_id": cid})
        out: dict[str, dict] = {}
        for cid, rec in replicas.items():
            if want_cid and cid != want_cid:
                continue
            if not rec.get("workspace_id"):
                # stamp identity from the authoritative container state —
                # never trust (or serve) an unattributed record across
                # tenants (same invariant as _ingest_runner_spans)
                state = await self.containers.get_state(cid)
                if state is not None:
                    rec.setdefault("workspace_id", state.workspace_id)
                    rec.setdefault("stub_id", state.stub_id)
            if want_stub and rec.get("stub_id", "") != want_stub:
                continue
            if not operator and rec.get("workspace_id") != ws.workspace_id:
                continue
            out[cid] = merge_record(rec, runner_halves.get(cid))
        return out

    async def _scaleout(self, request: web.Request) -> web.Response:
        """Scale-out plane report (ISSUE 17): per replica — multicast
        tree position (primary parent per group + children it re-serves),
        groups held/ready, execute-while-scaling readiness fraction, and
        bytes by tree edge from the coldstart record's per-peer split —
        joined from the coordinator's group ledger and the same merged
        coldstart records /api/v1/coldstart serves. Workspace-scoped the
        same way; ?container_id= / ?stub_id= filter identically."""
        if self.scaleout is None:
            return web.json_response(
                {"enabled": False, "replicas": [], "tree": {}})
        ws = self._ws(request)
        operator = self._is_operator(request)
        want_cid = request.query.get("container_id", "")
        want_stub = request.query.get("stub_id", "")
        records = await self._coldstart_records(ws, operator, want_cid,
                                                want_stub)
        from ..scaleout.coordinator import build_report
        snap = self.scaleout.ledger.snapshot()
        if not operator:
            # ledger rows carry no workspace; visibility comes from the
            # workspace-filtered record join (worker-id rows are
            # operator-only — they aggregate across tenants)
            snap = {k: v for k, v in snap.items() if k in records}
        if want_cid:
            snap = {k: v for k, v in snap.items() if k == want_cid}
        report = build_report(snap, self.scaleout.plan, records=records)
        report["enabled"] = True
        report["coordinator"] = self.scaleout.stats()
        return web.json_response(report)

    async def _postmortem(self, request: web.Request) -> web.Response:
        """Replica black-box records (ISSUE 14): the bounded forensic
        dumps engines leave behind on crash/OOM/watchdog-trip (last-K
        flight windows, recent spans, KV-pool + scheduler state, HBM
        breakdown, exception), shipped by the runner over
        ``/rpc/llm/postmortem`` and stored per container. Workspace-
        scoped like /api/v1/traces; ?container_id= pins one replica,
        ?stub_id= filters a deployment. The evidence survives the
        process it describes — the whole point of a black box."""
        ws = self._ws(request)
        operator = self._is_operator(request)
        want_cid = request.query.get("container_id", "")
        want_stub = request.query.get("stub_id", "")
        from ..observability.health import load_postmortems
        keys = [f"postmortem:{want_cid}"] if want_cid \
            else await self.store.keys("postmortem:*")
        out: dict[str, list] = {}
        for key in keys:
            records = await load_postmortems(self.store, key)
            if not records:
                continue
            cid = key.split(":", 1)[-1]
            # identity was stamped at ingest from the authenticated
            # container state; filter on it, never trust the payload
            visible = [r for r in records if isinstance(r, dict)
                       and (operator
                            or r.get("workspace_id") == ws.workspace_id)
                       and (not want_stub
                            or r.get("stub_id") == want_stub)]
            if visible:
                out[cid] = visible
        return web.json_response({"replicas": out})

    async def _flight(self, request: web.Request) -> web.Response:
        """Engine flight-recorder tail for one LLM deployment (ISSUE 8):
        proxies the runner's /flight RPC through the request buffer
        (?stub_id= required; ?container_id= pins a replica, ?limit= /
        ?since_seq= page the ring). Workspace-scoped via stub ownership.
        Routes like any invoke, so a scaled-to-zero deployment cold-starts
        a replica rather than answering from nothing."""
        stub = await self._stub_for(request, request.query.get("stub_id", ""))
        limit = int(self._q_float(request, "limit", 256))
        since_seq = int(self._q_float(request, "since_seq", 0))
        cid = request.query.get("container_id", "")
        result = await self.endpoints.forward(
            stub, "GET", f"/flight?limit={limit}&since_seq={since_seq}",
            [], b"", prefer=[cid] if cid else [],
            timeout_s=self.cfg.router.rpc_timeout_s)
        return web.Response(status=result.status, body=result.body,
                            content_type="application/json")

    async def _profile(self, request: web.Request) -> web.Response:
        """Arm jax.profiler on a live replica for the next N windows
        (ISSUE 8): body {stub_id, windows, container_id?}; returns the
        runner-side dump path immediately. The dump lands on the replica's
        filesystem — fetch it with `tpu9 shell`/volume tooling."""
        data = await request.json()
        stub = await self._stub_for(request, data.get("stub_id", ""))
        windows = int(data.get("windows", 8))
        cid = data.get("container_id", "")
        result = await self.endpoints.forward(
            stub, "POST", "/profile",
            [("Content-Type", "application/json")],
            json.dumps({"windows": windows,
                        "out_dir": data.get("out_dir", "")}).encode(),
            prefer=[cid] if cid else [],
            timeout_s=self.cfg.router.rpc_timeout_s)
        return web.Response(status=result.status, body=result.body,
                            content_type="application/json")

    async def _metrics(self, request: web.Request) -> web.Response:
        # fleet-wide registries (every worker's shipped counters) are
        # infrastructure state, not tenant data — operator-only, like
        # _traces' workspace scoping but for the whole surface
        self._require_operator(request)
        if request.query.get("format") == "prometheus":
            return web.Response(text=metrics.prometheus_text(),
                                content_type="text/plain")
        out = metrics.to_dict()
        # merge worker-shipped registries (fleet view)
        out["workers"] = {}
        for key in await self.store.keys("worker:metrics:*"):
            raw = await self.store.get(key)
            if raw:
                out["workers"][key.rsplit(":", 1)[-1]] = json.loads(raw)
        # cache-plane snapshots (ISSUE 13): per-worker tier/hedge/per-peer
        # evidence + warm weights pool occupancy, heartbeated by workers —
        # the restore/weight-distribution side of the fleet view
        out["cache"] = {}
        for key in await self.store.keys("worker:cache:*"):
            raw = await self.store.get(key)
            if raw:
                try:
                    out["cache"][key.rsplit(":", 1)[-1]] = json.loads(raw)
                except (ValueError, TypeError):
                    continue
        # per-engine serving stats (ISSUE 2 satellite): queue depth, active
        # streams, KV headroom, prefix hit rate — heartbeated by runners
        # into the pressure table, readable here without SSHing a node
        out["engines"] = {}
        for key in await self.store.keys("llm:pressure:*"):
            snap = await self.store.hgetall(key)
            if snap:
                out["engines"][key.rsplit(":", 1)[-1]] = snap
        if self.fleetobs is not None:
            # stale-replica aging (ISSUE 12): stamp last_seen/age_s from
            # the heartbeat; replicas silent > N beats are dropped rather
            # than served as live stats until the store TTL
            out["engines"] = self.fleetobs.filter_engines(out["engines"])
            # per-tenant / per-stub goodput decomposition joined against
            # usage.py chip-second buckets
            out["goodput"] = await self.fleetobs.metrics_section()
        if self.fleet_router is not None:
            out["router"] = self.fleet_router.snapshot_all()
        return web.json_response(out)

    async def _timeline(self, request: web.Request) -> web.Response:
        """Bounded in-gateway time-series rings (ISSUE 12): fleet history
        for the snapshot /api/v1/metrics can't answer. ?series=a,b,c
        (trailing * prefix-matches), ?since= (wall anchor), ?limit= newest
        N per series; no ?series= lists the available names."""
        self._require_operator(request)
        if self.fleetobs is None:
            return web.json_response({"error": "slo layer disabled"},
                                     status=404)
        limit = int(self._q_float(request, "limit", 0)) or None
        return web.json_response(self.fleetobs.timeline_payload(
            request.query.get("series", ""),
            self._q_float(request, "since", 0.0), limit))

    async def _slo(self, request: web.Request) -> web.Response:
        """Declared objectives + per-stub multi-window burn rates, with
        the pressure fold the autoscaler sees (ISSUE 12)."""
        self._require_operator(request)
        if self.fleetobs is None:
            return web.json_response({"error": "slo layer disabled"},
                                     status=404)
        return web.json_response(self.fleetobs.slo_payload())

    async def _events(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        rows = await self.events.query(
            kind_prefix=request.query.get("kind", ""),
            since=self._q_float(request, "since", 0.0),
            limit=int(self._q_float(request, "limit", 500)))
        # workspace scoping (same invariant _traces enforces): only the
        # operator sees the cluster-wide stream — container/task/deploy
        # events carry other tenants' ids and payloads
        if not self._is_operator(request):
            rows = [r for r in rows
                    if r.get("workspace_id") in ("", ws.workspace_id)]
        return web.json_response(rows)

    def _is_operator(self, request: web.Request) -> bool:
        try:
            self._require_operator(request)
            return True
        except web.HTTPForbidden:
            return False

    @staticmethod
    def _q_float(request: web.Request, name: str, default: float) -> float:
        """Query-param float with a 400 (not a 500) on garbage input."""
        raw = request.query.get(name)
        if raw is None:
            return default
        try:
            return float(raw)
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"{name} must be a number"}),
                content_type="application/json")

    async def _pools(self, request: web.Request) -> web.Response:
        self._ws(request)
        if self.pool_monitor is None:
            return web.json_response({})
        return web.json_response({
            name: vars(st) for name, st in self.pool_monitor.status.items()})

    # -- handlers: SDK RPC ----------------------------------------------------

    async def _rpc_auth_check(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response({"workspace_id": ws.workspace_id,
                                  "workspace_name": ws.name})

    async def _rpc_get_or_create_stub(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        data = await request.json()
        try:
            StubType(data.get("stub_type", ""))
        except ValueError:
            # fail loudly: an unknown type would silently boot the default
            # runner and e.g. never poll a task queue
            return web.json_response(
                {"error": f"unknown stub_type {data.get('stub_type')!r} "
                          f"(valid: {[t.value for t in StubType]})"},
                status=400)
        config = StubConfig.from_dict(data.get("config", {}))
        if (config.pricing is not None and config.pricing.enabled
                and not config.authorized):
            # pricing only bills authenticated external callers; on a
            # public endpoint every caller could go anonymous and free —
            # reject the combination instead of silently giving away
            # paid compute
            return web.json_response(
                {"error": "pricing requires authorized=True (a public "
                          "endpoint cannot be billed)"}, status=400)
        # HBM feasibility gate for declarative LLM deployments (VERDICT
        # r03 #8): weights + KV + scratch must fit the slice's HBM, proven
        # arithmetically HERE — not discovered as an OOM on real chips.
        # Applies when the stub declares its model (extra.model); app-code
        # engines (load() in user code) can't be checked statically.
        if (config.extra.get("runner") == "llm"
                and config.extra.get("model") and config.runtime.tpu):
            from ..serving.feasibility import (InfeasibleDeployment,
                                               validate_llm_deployment)
            try:
                budget = validate_llm_deployment(
                    config.extra["model"], config.runtime.tpu,
                    max_batch=int(config.extra.get("max_batch", 8)),
                    max_seq_len=int(config.extra.get("max_seq_len", 2048)),
                    tp=int(config.extra.get("tp", 0)))
            except InfeasibleDeployment as exc:
                return web.json_response({"error": str(exc)}, status=400)
            except (KeyError, ValueError) as exc:
                return web.json_response(
                    {"error": f"llm config invalid: {exc}"}, status=400)
            config.extra["hbm_budget"] = budget.as_dict()
        stub = await self.backend.get_or_create_stub(
            workspace_id=ws.workspace_id,
            name=data["name"],
            stub_type=data["stub_type"],
            config=config,
            object_id=data.get("object_id", ""),
            app_name=data.get("app_name", ""),
            force_create=data.get("force_create", False))
        return web.json_response({"stub_id": stub.stub_id})

    async def _rpc_put_object(self, request: web.Request) -> web.Response:
        """Workspace code upload (reference PutObjectStream, gateway.proto:36).
        Body: raw zip bytes; dedupe by hash."""
        ws = self._ws(request)
        body = await request.read()
        obj_hash = hashlib.sha256(body).hexdigest()
        existing = await self.backend.find_object_by_hash(ws.workspace_id,
                                                          obj_hash)
        if existing:
            return web.json_response({"object_id": existing["object_id"],
                                      "deduped": True})
        objects_dir = os.path.join(self.cfg.storage.local_root,
                                   ws.workspace_id, "objects")
        os.makedirs(objects_dir, exist_ok=True)
        path = os.path.join(objects_dir, f"{obj_hash}.zip")
        # off-loop tmp+rename (ASY004): zips are MBs, and concurrent
        # same-hash uploads racing a _rpc_get_object reader must never
        # see a half-written or re-truncated file
        await atomic_write_bytes(path, body)
        object_id = await self.backend.create_object(ws.workspace_id, obj_hash,
                                                     len(body), path)
        return web.json_response({"object_id": object_id, "deduped": False})

    async def _rpc_get_object(self, request: web.Request) -> web.Response:
        """Workers (cross-workspace, worker token) and owners download synced
        code archives here (reference: repo-over-gRPC object access)."""
        ws = self._ws(request)
        obj = await self.backend.get_object(request.match_info["object_id"])
        if obj is None or (not request.get("is_worker")
                           and obj["workspace_id"] != ws.workspace_id):
            return web.json_response({"error": "object not found"},
                                     status=404)
        return web.FileResponse(obj["path"])

    async def _rpc_deploy(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        data = await request.json()
        stub = await self.backend.get_stub(data["stub_id"])
        if stub is None or stub.workspace_id != ws.workspace_id:
            return web.json_response({"error": "stub not found"}, status=404)
        dep = await self.backend.create_deployment(
            ws.workspace_id, data["name"], stub.stub_id, app_id=stub.app_id)
        # warm the instance immediately (InstanceController warmup)
        if stub.stub_type in (StubType.ENDPOINT.value, StubType.ASGI.value,
                              StubType.REALTIME.value):
            await self.endpoints.get_or_create_instance(stub)
        elif stub.stub_type == StubType.TASK_QUEUE.value:
            await self.taskqueues.get_or_create_instance(stub)
        invoke_url = (f"http://{self.cfg.gateway.host}:{self.port}"
                      f"/endpoint/{dep.name}")
        return web.json_response({"deployment_id": dep.deployment_id,
                                  "version": dep.version,
                                  "subdomain": dep.subdomain,
                                  "invoke_url": invoke_url})

    # -- handlers: tasks / queues / functions ---------------------------------

    async def _stub_for(self, request: web.Request, stub_id: str) -> Stub:
        ws = self._ws(request)
        stub = await self.backend.get_stub(stub_id)
        if stub is None or stub.workspace_id != ws.workspace_id:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "stub not found"}),
                content_type="application/json")
        return stub

    async def _rpc_tq_put(self, request: web.Request) -> web.Response:
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        msg = await self.taskqueues.put(stub, data.get("args", []),
                                        data.get("kwargs", {}))
        return web.json_response({"task_id": msg.task_id})

    async def _rpc_tq_pop(self, request: web.Request) -> web.Response:
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        msg = await self._bounded_longpoll(self.taskqueues.pop(
            stub.workspace_id, stub.stub_id, data.get("container_id", ""),
            timeout=min(float(data.get("timeout", 25.0)), 30.0)))
        if msg is None:
            return web.json_response({"task": None})
        return web.json_response({"task": {
            "task_id": msg.task_id, "args": msg.handler_args,
            "kwargs": msg.handler_kwargs, "retry_count": msg.retry_count}})

    async def _rpc_tq_status(self, request: web.Request) -> web.Response:
        stub = await self._stub_for(request, request.match_info["stub_id"])
        return web.json_response(await self.taskqueues.queue_status(stub))

    async def _rpc_fn_invoke(self, request: web.Request) -> web.Response:
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        policy = None
        if "policy" in data:
            policy = TaskPolicy.from_dict(data["policy"])
        msg = await self.functions.invoke(stub, data.get("args", []),
                                          data.get("kwargs", {}), policy)
        if not data.get("wait", True):
            return web.json_response({"task_id": msg.task_id})
        # cap the blocking wait under client/proxy timeouts; callers poll the
        # result route with the task_id after a 504
        wait_s = float(data.get("timeout") or stub.config.timeout_s or 60.0)
        result = await self.dispatcher.retrieve(msg.task_id,
                                                timeout=min(max(wait_s, 1.0),
                                                            110.0))
        if result is None:
            return web.json_response({"task_id": msg.task_id,
                                      "error": "timeout waiting for result"},
                                     status=504)
        return web.json_response({"task_id": msg.task_id, **result})

    async def _rpc_schedule_register(self, request: web.Request) -> web.Response:
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        if stub.stub_type not in (StubType.SCHEDULE.value,
                                  StubType.FUNCTION.value):
            return web.json_response(
                {"error": f"schedules require a function/schedule stub, "
                          f"got {stub.stub_type}"}, status=400)
        try:
            schedule_id = await self.functions.register_schedule(
                stub, data["cron"])
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"schedule_id": schedule_id})

    async def _task_for(self, request: web.Request):
        """Workspace-scoped task lookup (404 on missing or foreign tasks)."""
        ws = self._ws(request)
        task_id = request.match_info["task_id"]
        msg = await self.dispatcher.tasks.get_message(task_id)
        if msg is None or msg.workspace_id != ws.workspace_id:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "task not found"}),
                content_type="application/json")
        return msg

    async def _rpc_task_get(self, request: web.Request) -> web.Response:
        msg = await self._task_for(request)
        return web.json_response({"task_id": msg.task_id, "status": msg.status,
                                  "args": msg.handler_args,
                                  "kwargs": msg.handler_kwargs,
                                  "container_id": msg.container_id})

    async def _rpc_task_result(self, request: web.Request) -> web.Response:
        msg = await self._task_for(request)
        timeout = min(self._q_float(request, "timeout", 0.0), 110.0)
        result = await self._bounded_longpoll(
            self.dispatcher.retrieve(msg.task_id, timeout=timeout))
        if result is None:
            return web.json_response({"pending": True}, status=202)
        return web.json_response(result)

    async def _rpc_task_claim(self, request: web.Request) -> web.Response:
        msg = await self._task_for(request)
        data = await request.json()
        claimed = await self.dispatcher.claim(msg.task_id,
                                              data.get("container_id", ""))
        return web.json_response({"ok": claimed is not None})

    async def _rpc_task_complete(self, request: web.Request) -> web.Response:
        msg = await self._task_for(request)
        data = await request.json()
        ok = await self.dispatcher.complete(
            msg.task_id, result=data.get("result"),
            error=data.get("error"),
            container_id=data.get("container_id", "")) is not None
        return web.json_response({"ok": ok})

    async def _rpc_task_cancel(self, request: web.Request) -> web.Response:
        msg = await self._task_for(request)
        return web.json_response({"ok": await self.dispatcher.cancel(msg.task_id)})

    async def _rpc_llm_pressure(self, request: web.Request) -> web.Response:
        """Engine pressure heartbeat from LLM runners (pod/llm.go:460
        equivalent). Workspace-scoped: a tenant can only report pressure for
        its own containers."""
        ws = self._ws(request)
        d = await request.json()
        state = await self.containers.get_state(d.get("container_id", ""))
        if state is None or state.workspace_id != ws.workspace_id:
            return web.json_response({"error": "container not found"},
                                     status=404)
        from ..abstractions.llm import LlmRouter
        router = LlmRouter(self.store)
        await router.record_pressure(
            state.container_id, float(d.get("token_pressure", 0.0)),
            int(d.get("active_streams", 0)), extra=d.get("extra"))
        if self.fleetobs is not None:
            # timeline + goodput sampling rides the heartbeat cadence
            # (ISSUE 12) — same accepted-beat channel the spans use
            self.fleetobs.ingest_heartbeat(
                state.container_id, state.workspace_id, state.stub_id,
                float(d.get("token_pressure", 0.0)),
                int(d.get("active_streams", 0)),
                extra=d.get("extra") if isinstance(d.get("extra"), dict)
                else None)
        spans = d.get("spans")
        if isinstance(spans, list) and spans:
            await self._ingest_runner_spans(state, spans)
        decisions = d.get("decisions")
        if isinstance(decisions, list) and decisions:
            await self._ingest_runner_decisions(state, decisions)
        return web.json_response({"ok": True})

    async def _rpc_llm_postmortem(self, request: web.Request) -> web.Response:
        """Black-box ingest (ISSUE 14): a dying/wedged engine's forensic
        record, shipped by the runner. Workspace-scoped like the pressure
        heartbeat; identity is stamped HERE from the authenticated
        container state (a tenant must not plant records into another
        workspace's /api/v1/postmortem view), the record re-clamped to
        the size bound server-side (the runner's clamp is not trusted),
        and the per-replica list kept at the last N records."""
        ws = self._ws(request)
        d = await request.json()
        state = await self.containers.get_state(d.get("container_id", ""))
        if state is None or state.workspace_id != ws.workspace_id:
            return web.json_response({"error": "container not found"},
                                     status=404)
        rec = d.get("record")
        if not isinstance(rec, dict):
            return web.json_response({"error": "record must be a dict"},
                                     status=400)
        from ..observability.health import (clamp_postmortem,
                                            store_postmortem)
        rec = clamp_postmortem(rec)
        rec["workspace_id"] = state.workspace_id
        rec["stub_id"] = state.stub_id
        rec["container_id"] = state.container_id
        # atomic list append: the worker's exit record for the same
        # container may land concurrently from another process
        await store_postmortem(self.store, state.container_id, rec)
        log.warning("post-mortem stored for %s (%s)",
                    state.container_id, rec.get("reason", ""))
        return web.json_response({"ok": True})

    async def _ingest_runner_spans(self, state, spans: list) -> None:
        """Engine/runner spans riding the pressure heartbeat (ISSUE 8 —
        the same channel worker rings use). The workspace stamp is applied
        HERE from the authenticated container state, never trusted from
        the runner payload: a tenant container must not be able to plant
        spans into another workspace's /api/v1/traces view."""
        cleaned = []
        for sp in spans[:2048]:         # bound one beat's ingest
            if not isinstance(sp, dict) or not sp.get("traceId"):
                continue
            attrs = sp.get("attributes")
            if not isinstance(attrs, dict):
                attrs = {}
            attrs["workspace_id"] = state.workspace_id
            attrs["container_id"] = state.container_id
            sp["attributes"] = attrs
            cleaned.append(sp)
        if not cleaned:
            return
        key = f"runner:traces:{state.container_id}"
        existing = await self.store.get(key)
        try:
            merged = (json.loads(existing) if existing else [])[-1500:]
        except (ValueError, TypeError):
            merged = []
        merged.extend(cleaned)
        await self.store.set(key, json.dumps(merged), ttl=3600.0)

    async def _ingest_runner_decisions(self, state, decisions: list) -> None:
        """Runner decision records riding the pressure heartbeat (ISSUE
        19 — the same accepted-beat channel the engine spans use, so the
        runner's seq watermark only advances on a 2xx). Identity is
        stamped HERE from the authenticated container state, never
        trusted from the payload: a tenant container must not plant
        decision evidence into another workspace's /api/v1/decisions."""
        cleaned = []
        for rec in decisions[:1024]:    # bound one beat's ingest
            if not isinstance(rec, dict) or not rec.get("plane"):
                continue
            rec["workspace_id"] = state.workspace_id
            rec["container_id"] = state.container_id
            cleaned.append(rec)
        if not cleaned:
            return
        key = f"runner:decisions:{state.container_id}"
        existing = await self.store.get(key)
        try:
            merged = (json.loads(existing) if existing else [])[-1000:]
        except (ValueError, TypeError):
            merged = []
        merged.extend(cleaned)
        await self.store.set(key, json.dumps(merged), ttl=3600.0)

    # -- handlers: pods ---------------------------------------------------------

    async def _pod_container_for(self, request: web.Request):
        return await self._container_for(request, key="container_id",
                                         allow_worker=False)

    # -- bot (petri-net orchestration; pkg/abstractions/experimental/bot) ----

    async def _rpc_bot_session_create(self, request: web.Request) -> web.Response:
        from ..abstractions.bot import BotError
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        try:
            return web.json_response(await self.bots.create_session(stub))
        except BotError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(e)}),
                                     content_type="application/json")

    async def _rpc_bot_sessions(self, request: web.Request) -> web.Response:
        stub = await self._stub_for(request, request.match_info["stub_id"])
        return web.json_response(await self.bots.list_sessions(stub))

    async def _rpc_bot_session_delete(self, request: web.Request) -> web.Response:
        from ..abstractions.bot import BotError
        stub = await self._stub_for(request, request.match_info["stub_id"])
        try:
            ok = await self.bots.delete_session(
                stub, request.match_info["session_id"])
        except BotError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(e)}),
                                     content_type="application/json")
        return web.json_response({"ok": ok})

    async def _rpc_bot_push(self, request: web.Request) -> web.Response:
        from ..abstractions.bot import BotError
        from ..schema import ValidationError
        stub = await self._stub_for(request, request.match_info["stub_id"])
        data = await request.json()
        try:
            out = await self.bots.push_marker(
                stub, request.match_info["session_id"],
                data["location"], data.get("marker", {}))
        except (BotError, ValidationError) as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(e)}),
                                     content_type="application/json")
        return web.json_response(out)

    async def _rpc_bot_pop(self, request: web.Request) -> web.Response:
        from ..abstractions.bot import BotError
        stub = await self._stub_for(request, request.match_info["stub_id"])
        data = await request.json()
        try:
            marker = await self.bots.pop_marker(
                stub, request.match_info["session_id"], data["location"])
        except BotError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(e)}),
                                     content_type="application/json")
        return web.json_response({"marker": marker})

    async def _rpc_bot_state(self, request: web.Request) -> web.Response:
        from ..abstractions.bot import BotError
        stub = await self._stub_for(request, request.match_info["stub_id"])
        try:
            return web.json_response(await self.bots.session_state(
                stub, request.match_info["session_id"]))
        except BotError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(e)}),
                                     content_type="application/json")

    async def _rpc_bot_events(self, request: web.Request) -> web.Response:
        stub = await self._stub_for(request, request.match_info["stub_id"])
        # ownership: events are keyed by session, session list is per stub
        session_id = request.match_info["session_id"]
        if await self.bots.get_session(stub, session_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "session not found"}),
                content_type="application/json")
        entries = await self.bots.events(
            session_id, last_id=request.query.get("since", "0"))
        return web.json_response([{"id": eid, **e} for eid, e in entries])

    async def _rpc_pod_create(self, request: web.Request) -> web.Response:
        data = await request.json()
        stub = await self._stub_for(request, data["stub_id"])
        from_snapshot = data.get("from_snapshot", "")
        from_criu = data.get("from_criu_snapshot", "")
        for snap_id, want_kind in ((from_snapshot, "workdir"),
                                   (from_criu, "criu")):
            if snap_id:
                # snapshots are workspace-scoped (foreign ids 404) AND
                # kind-checked: feeding a workdir snapshot to criu restore
                # (or CRIU images to a working tree) must fail loudly here
                snap = await self.backend.get_sandbox_snapshot(snap_id)
                if snap is None or snap["workspace_id"] != stub.workspace_id:
                    return web.json_response({"error": "snapshot not found"},
                                             status=404)
                if snap.get("kind", "workdir") != want_kind:
                    return web.json_response(
                        {"error": f"snapshot {snap_id} is "
                                  f"{snap.get('kind')!r}, not {want_kind!r}"},
                        status=400)
        out = await self.pods.create(stub, name=data.get("name", ""),
                                     from_snapshot=from_snapshot,
                                     from_criu_snapshot=from_criu)
        if data.get("wait", True):
            address = await self.pods.wait_running(
                out["container_id"],
                timeout=min(float(data.get("timeout", 60.0)), 110.0))
            out["address"] = address
            out["running"] = address is not None
        return web.json_response(out)

    async def _rpc_pod_status(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        return web.json_response(state.to_dict())

    async def _rpc_pod_exec(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        data = await request.json()
        out = await self.pods.exec(state.container_id,
                                   list(data.get("cmd", [])),
                                   timeout=min(float(data.get("timeout", 60)),
                                               110.0))
        return web.json_response(out)

    # -- handlers: sandbox depth (process mgr / fs / snapshots) --------------

    async def _rpc_sbx_spawn(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        data = await request.json()
        out = await self.pods.sbx(state.container_id, {
            "op": "spawn", "cmd": list(data.get("cmd", []))})
        return web.json_response(out)

    async def _rpc_sbx_ps(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        return web.json_response(
            await self.pods.sbx(state.container_id, {"op": "ps"}))

    async def _rpc_sbx_status(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        return web.json_response(await self.pods.sbx(
            state.container_id,
            {"op": "status", "proc_id": request.match_info["proc_id"]}))

    async def _rpc_sbx_stdin(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        data = await request.json()
        return web.json_response(await self.pods.sbx(
            state.container_id,
            {"op": "stdin", "proc_id": request.match_info["proc_id"],
             "data": data.get("data", "")}))

    async def _rpc_sbx_kill(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        return web.json_response(await self.pods.sbx(
            state.container_id,
            {"op": "kill", "proc_id": request.match_info["proc_id"]}))

    async def _rpc_sbx_out(self, request: web.Request) -> web.Response:
        # tenancy: the container lookup gates access, and the proc must
        # belong to that container. Pairing is verified against the worker
        # ONCE and cached — subsequent output polls read straight off the
        # state bus with no worker round-trip (wait() polls at ~5 Hz).
        state = await self._pod_container_for(request)
        proc_id = request.match_info["proc_id"]
        if self._sbx_proc_owner.get(proc_id) != state.container_id:
            check = await self.pods.sbx(
                state.container_id, {"op": "status", "proc_id": proc_id})
            if check.get("error"):
                return web.json_response(check, status=404)
            if len(self._sbx_proc_owner) > 10000:
                self._sbx_proc_owner.clear()
            self._sbx_proc_owner[proc_id] = state.container_id
        out = await self.pods.proc_output(
            proc_id,
            last_id=request.query.get("last_id", "0"),
            timeout=min(self._q_float(request, "timeout", 0.0), 30.0))
        return web.json_response(out)

    async def _rpc_sbx_fs(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        data = await request.json()
        out = await self.pods.sbx(state.container_id, {
            "op": "fs", "fs_op": data.get("op", ""),
            "path": data.get("path", ""), "data": data.get("data", "")})
        return web.json_response(out)

    async def _rpc_sbx_snapshot(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        out = await self.pods.sbx(state.container_id, {
            "op": "snapshot", "workspace_id": state.workspace_id},
            timeout=120.0)
        return web.json_response(out)

    async def _rpc_criu_checkpoint(self, request: web.Request) -> web.Response:
        """CPU process-tree checkpoint (criu.go:668 analogue); restore by
        creating a pod with from_criu_snapshot."""
        state = await self._pod_container_for(request)
        out = await self.pods.sbx(state.container_id, {
            "op": "criu_checkpoint", "workspace_id": state.workspace_id},
            timeout=300.0)
        return web.json_response(out)

    async def _rpc_sbx_snapshots(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(
            await self.backend.list_sandbox_snapshots(ws.workspace_id))

    async def _pod_proxy(self, request: web.Request) -> web.Response:
        state = await self._pod_container_for(request)
        if not state.address:
            return web.json_response({"error": "pod not running"}, status=503)
        import aiohttp as _aiohttp
        tail = request.match_info.get("tail", "")
        address = state.address
        if self.dialer is not None:
            address = await self.dialer.ensure_route(address, state.worker_id)
        url = f"http://{address}/{tail}"
        if request.query_string:
            url += f"?{request.query_string}"
        # forward end-to-end headers, not hop-by-hop/host ones
        fwd_headers = {k: v for k, v in request.headers.items()
                       if k.lower() not in ("host", "connection",
                                            "transfer-encoding",
                                            "content-length",
                                            "authorization")}
        body = await request.read()
        if self._proxy_session is None or self._proxy_session.closed:
            self._proxy_session = _aiohttp.ClientSession()
        try:
            async with self._proxy_session.request(
                    request.method, url, data=body or None,
                    headers=fwd_headers,
                    timeout=_aiohttp.ClientTimeout(total=110)) as resp:
                out = await resp.read()
                proxied = web.Response(status=resp.status, body=out)
                proxied.headers["Content-Type"] = resp.headers.get(
                    "Content-Type", "application/octet-stream")
                return proxied
        except (_aiohttp.ClientError, asyncio.TimeoutError) as exc:
            return web.json_response({"error": type(exc).__name__},
                                     status=502)

    # -- handlers: primitives ---------------------------------------------------

    async def _rpc_map(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        name = request.match_info["name"]
        d = await request.json()
        op = d.get("op")
        try:
            if op == "set":
                await self.maps.set(ws.workspace_id, name, d["field"],
                                    d.get("value"))
                return web.json_response({"ok": True})
            if op == "get":
                return web.json_response({"value": await self.maps.get(
                    ws.workspace_id, name, d["field"])})
            if op == "delete":
                return web.json_response({"ok": await self.maps.delete(
                    ws.workspace_id, name, d["field"])})
            if op == "keys":
                return web.json_response({"keys": await self.maps.keys(
                    ws.workspace_id, name)})
            if op == "items":
                return web.json_response({"items": await self.maps.items(
                    ws.workspace_id, name)})
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"error": f"bad op {op!r}"}, status=400)

    async def _rpc_queue(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        name = request.match_info["name"]
        d = await request.json()
        op = d.get("op")
        try:
            if op == "push":
                depth = await self.queues.push(ws.workspace_id, name,
                                               d.get("value"))
                return web.json_response({"depth": depth})
            if op == "pop":
                value = await self.queues.pop(
                    ws.workspace_id, name,
                    timeout=min(float(d.get("timeout", 0)), 30.0))
                return web.json_response({"value": value})
            if op == "depth":
                return web.json_response({"depth": await self.queues.depth(
                    ws.workspace_id, name)})
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"error": f"bad op {op!r}"}, status=400)

    async def _rpc_signal(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        name = request.match_info["name"]
        d = await request.json()
        op = d.get("op")
        if op == "set":
            await self.signals.set(ws.workspace_id, name, ttl=d.get("ttl"))
            return web.json_response({"ok": True})
        if op == "clear":
            await self.signals.clear(ws.workspace_id, name)
            return web.json_response({"ok": True})
        if op == "is_set":
            return web.json_response({"set": await self.signals.is_set(
                ws.workspace_id, name)})
        if op == "wait":
            fired = await self.signals.wait(
                ws.workspace_id, name,
                timeout=min(float(d.get("timeout", 30.0)), 60.0))
            return web.json_response({"set": fired})
        return web.json_response({"error": f"bad op {op!r}"}, status=400)

    async def _rpc_output_save(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        filename = request.query.get("filename", "output.bin")
        data = await request.read()
        try:
            output_id = await self.outputs.save(ws.workspace_id, filename,
                                                data)
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({
            "output_id": output_id,
            "url": f"/rpc/output/{output_id}"})

    async def _rpc_output_get(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        path = await self.outputs.path(ws.workspace_id,
                                       request.match_info["output_id"])
        if path is None:
            return web.json_response({"error": "output not found"},
                                     status=404)
        return web.FileResponse(path)

    async def _list_volumes(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(await self.backend.list_volumes(
            ws.workspace_id))

    async def _create_volume(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        vol = await self.volume_files.ensure(ws.workspace_id,
                                             request.match_info["name"])
        return web.json_response(vol)

    async def _delete_volume(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        ok = await self.backend.delete_volume(ws.workspace_id,
                                              request.match_info["name"])
        return web.json_response({"ok": ok})

    async def _volume_list(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(await self.volume_files.list(
            ws.workspace_id, request.match_info["name"],
            prefix=request.query.get("prefix", "")))

    async def _volume_put(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        data = await request.read()
        try:
            n = await self.volume_files.write(
                ws.workspace_id, request.match_info["name"],
                request.match_info["path"], data)
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"size": n})

    async def _volume_get(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        try:
            data = await self.volume_files.read(
                ws.workspace_id, request.match_info["name"],
                request.match_info["path"])
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        if data is None:
            return web.json_response({"error": "file not found"}, status=404)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _volume_delete(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        try:
            ok = await self.volume_files.delete(
                ws.workspace_id, request.match_info["name"],
                request.match_info["path"])
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"ok": ok})

    def _require_worker(self, request: web.Request) -> None:
        self._ws(request)
        if not request.get("is_worker"):
            raise web.HTTPForbidden(
                text=json.dumps({"error": "worker token required"}),
                content_type="application/json")

    async def _internal_volume_list(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        entries = await self.volume_files.list(
            request.match_info["workspace_id"], request.match_info["name"])
        return web.json_response(entries)

    async def _internal_volume_manifest(self,
                                        request: web.Request) -> web.Response:
        """Chunk manifest of a workspace volume (VERDICT r04 #5): workers
        CacheFS-mount it read-through instead of syncing the whole volume
        down — a container is ready before a multi-GB volume is local, and
        page faults stream exactly the chunks touched. Chunks land in the
        same content-addressed store as image chunks (the worker cache's
        source path already knows how to fetch them). Recomputed only when
        the volume's listing fingerprint (paths+sizes+mtimes) moves."""
        self._require_worker(request)
        ws = request.match_info["workspace_id"]
        name = request.match_info["name"]
        entries = await self.volume_files.list(ws, name)
        fingerprint = hashlib.sha256(json.dumps(
            sorted([e["path"], e["size"], e.get("mtime") or 0]
                   for e in entries), sort_keys=True,
            default=str).encode()).hexdigest()
        cached = self._volume_manifest_cache.get((ws, name))
        if cached is not None and cached[0] == fingerprint:
            return web.Response(text=cached[1],
                                content_type="application/json")
        # chunking a multi-GB volume takes longer than a worker's request
        # timeout — build in a background task, answer within a bounded
        # wait, and return 503 if still building (the worker falls back to
        # sync-down for THIS container; the next mount hits the cache).
        # Keyed by FINGERPRINT: awaiting an in-flight build for an older
        # listing would return a stale manifest as if it were current
        key = (ws, name, fingerprint)
        for k in [k for k, t in self._volume_manifest_builds.items()
                  if t.done()]:
            del self._volume_manifest_builds[k]
        build = self._volume_manifest_builds.get(key)
        if build is None:
            build = asyncio.create_task(
                self._build_volume_manifest(ws, name, entries, fingerprint))
            self._volume_manifest_builds[key] = build
        try:
            blob = await asyncio.wait_for(asyncio.shield(build),
                                          timeout=120.0)
        except asyncio.TimeoutError:
            return web.json_response(
                {"error": "manifest build in progress"}, status=503)
        except Exception as exc:        # noqa: BLE001 — surface, don't 500
            return web.json_response(
                {"error": f"manifest build failed: {exc}"}, status=503)
        return web.Response(text=blob, content_type="application/json")

    async def _build_volume_manifest(self, ws: str, name: str,
                                     entries: list, fingerprint: str) -> str:
        from ..images.manifest import DEFAULT_CHUNK, FileEntry, ImageManifest
        manifest = ImageManifest(
            image_id=f"vol-{ws}-{name}-{fingerprint[:12]}", kind="env")

        def _hash_and_store(blob: bytes) -> str:
            digest = hashlib.sha256(blob).hexdigest()
            self.images.accept_chunk(digest, blob)
            return digest

        for e in entries:
            # ranged reads + per-chunk thread hops: a multi-GB file never
            # buffers whole in gateway RAM, and the event loop keeps
            # serving between chunks
            chunks = []
            size = 0
            for off in range(0, int(e["size"]), DEFAULT_CHUNK):
                blob = await self.volume_files.read_range(
                    ws, name, e["path"], off, DEFAULT_CHUNK)
                if not blob:
                    break               # file shrank/vanished mid-walk
                chunks.append(await asyncio.to_thread(_hash_and_store,
                                                      blob))
                size += len(blob)
            manifest.files.append(FileEntry(
                path=e["path"], mode=0o644, size=size, chunks=chunks))
            manifest.total_bytes += size
        blob = manifest.to_json()
        self._volume_manifest_cache[(ws, name)] = (fingerprint, blob)
        return blob

    async def _internal_volume_get(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        try:
            data = await self.volume_files.read(
                request.match_info["workspace_id"],
                request.match_info["name"], request.match_info["path"])
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        if data is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _internal_volume_put(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        data = await request.read()
        try:
            n = await self.volume_files.write(
                request.match_info["workspace_id"],
                request.match_info["name"], request.match_info["path"], data)
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"size": n})

    async def _volume_mp_initiate(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        try:
            upload_id = await self.volume_files.multipart_initiate(
                ws.workspace_id, request.match_info["name"],
                request.match_info["path"])
        except PrimitiveError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"upload_id": upload_id})

    async def _volume_mp_part(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        data = await request.read()
        try:
            await self.volume_files.multipart_put_part(
                ws.workspace_id, request.match_info["upload_id"],
                int(request.match_info["index"]), data)
        except (PrimitiveError, ValueError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"ok": True, "size": len(data)})

    async def _volume_mp_complete(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        body = await request.json()
        try:
            size = await self.volume_files.multipart_complete(
                ws.workspace_id, request.match_info["upload_id"],
                int(body.get("parts", 0)))
        except (PrimitiveError, ValueError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"ok": True, "size": size})

    async def _volume_mp_abort(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        ok = await self.volume_files.multipart_abort(
            ws.workspace_id, request.match_info["upload_id"])
        return web.json_response({"ok": ok})

    # -- handlers: images ------------------------------------------------------

    async def _rpc_image_verify(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        spec = ImageSpec.from_dict(await request.json())
        return web.json_response(
            await self.images.verify(spec, workspace_id=ws.workspace_id))

    async def _rpc_image_build(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        spec = ImageSpec.from_dict(await request.json())
        return web.json_response(await self.images.build(ws.workspace_id,
                                                         spec))

    async def _image_access_ok(self, request: web.Request,
                               image_id: str) -> bool:
        """Workspace scoping for image reads: worker tokens (the pullers)
        see everything, user tokens only their own workspace's images.
        Manifests bake in spec.env so cross-tenant reads leak secrets."""
        if request.get("is_worker"):
            return True
        ws = self._ws(request)
        row = await self.backend.get_image(image_id)
        if row is not None and row["workspace_id"] == ws.workspace_id:
            return True
        # dedupe case: the build/verify call granted an access row even
        # though another workspace owns the image record
        return await self.backend.has_image_access(image_id, ws.workspace_id)

    async def _rpc_image_status(self, request: web.Request) -> web.Response:
        image_id = request.match_info["image_id"]
        if not await self._image_access_ok(request, image_id):
            return web.json_response({"error": "image not found"}, status=404)
        return web.json_response(await self.images.status(image_id))

    async def _rpc_image_manifest(self, request: web.Request) -> web.Response:
        image_id = request.match_info["image_id"]
        if not await self._image_access_ok(request, image_id):
            return web.json_response({"error": "image not found"}, status=404)
        blob = self.images.manifest_json(image_id)
        if blob is None:
            return web.json_response({"error": "image not found"}, status=404)
        return web.Response(text=blob, content_type="application/json")

    async def _rpc_image_chunk(self, request: web.Request) -> web.Response:
        # Chunks are content-addressed and shared across images, so a bare
        # digest can't be workspace-scoped. Workers (the only pull path) may
        # read any chunk; user tokens must name an image they own whose
        # manifest actually contains the digest.
        self._ws(request)
        digest = request.match_info["digest"]
        if not request.get("is_worker"):
            image_id = request.query.get("image_id", "")
            if not await self._image_access_ok(request, image_id):
                return web.json_response({"error": "chunk not found"},
                                         status=404)
            m = self.images.builder.load_manifest(image_id)
            if m is None or digest not in m.all_chunks():
                return web.json_response({"error": "chunk not found"},
                                         status=404)
        data = self.images.chunk(digest)
        if data is None:
            return web.json_response({"error": "chunk not found"}, status=404)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _image_uploader_ws(self, request: web.Request,
                                 image_id: str) -> Optional[str]:
        """Authorize a build-runner upload. STRICTER than read access: only
        the workspace that owns the image ROW (the build requester — whose
        runner token the build container carries) may upload, not
        dedupe-granted readers; otherwise any tenant who proves knowledge of
        a spec could overwrite the shared image other tenants execute.
        Returns the workspace id to record, or None → 404."""
        ws = self._ws(request)
        if request.get("is_worker"):
            return ws.workspace_id
        row = await self.backend.get_image(image_id)
        if row is not None and row["workspace_id"] == ws.workspace_id:
            return ws.workspace_id
        return None

    async def _rpc_image_chunk_put(self, request: web.Request) -> web.Response:
        # chunks are content-addressed and verified against their digest, so
        # any authenticated runner may contribute them (a bad upload can't
        # poison another image — mismatches are rejected)
        self._ws(request)
        digest = request.match_info["digest"]
        data = await request.read()
        if not self.images.accept_chunk(digest, data):
            return web.json_response({"error": "digest mismatch"}, status=400)
        return web.json_response({"ok": True})

    async def _rpc_image_manifest_put(self, request: web.Request) -> web.Response:
        image_id = request.match_info["image_id"]
        workspace_id = await self._image_uploader_ws(request, image_id)
        if workspace_id is None:
            return web.json_response({"error": "image not found"}, status=404)
        out = await self.images.accept_manifest(
            image_id, workspace_id, await request.text())
        if "error" in out:
            return web.json_response(out, status=400)
        return web.json_response(out)

    async def _rpc_image_complete(self, request: web.Request) -> web.Response:
        image_id = request.match_info["image_id"]
        workspace_id = await self._image_uploader_ws(request, image_id)
        if workspace_id is None:
            return web.json_response({"error": "image not found"}, status=404)
        data = await request.json()
        await self.images.complete(image_id, workspace_id,
                                   bool(data.get("ok")),
                                   list(data.get("logs", [])))
        return web.json_response({"ok": True})

    # -- handlers: invoke ------------------------------------------------------

    async def _subdomain_invoke(self, request: web.Request) -> web.Response:
        host = request.headers.get("Host", "").split(":")[0]
        sub = host.split(".")[0] if "." in host else ""
        dep = await self.backend.get_deployment_by_subdomain(sub) if sub \
            else None
        if dep is None:
            return web.json_response({"error": "not found"}, status=404)
        return await self._serve_deployment(
            request, dep, request.match_info.get("tail", ""))

    async def _invoke(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        tail = request.match_info.get("tail", "")
        ws = request.get("workspace")
        workspace_id = ws.workspace_id if ws else None

        dep = None
        if workspace_id:
            dep = await self.backend.get_deployment(workspace_id, name)
        if dep is None:
            dep = await self.backend.get_deployment_by_subdomain(name)
        if dep is None and not workspace_id:
            return web.json_response({"error": "unauthorized"}, status=401)
        if dep is None:
            return web.json_response({"error": f"no deployment {name!r}"},
                                     status=404)
        return await self._serve_deployment(request, dep, tail)

    async def _serve_deployment(self, request: web.Request, dep,
                                tail: str) -> web.Response:
        ws = request.get("workspace")
        stub = await self.backend.get_stub(dep.stub_id)
        if stub is None:
            return web.json_response({"error": "stub missing"}, status=500)
        pricing = stub.config.pricing
        external = ws is not None and ws.workspace_id != stub.workspace_id
        # a priced deployment is invokable by OTHER authenticated workspaces
        # (reference deployment.go:91: pricing overrides the owner-only
        # check). Billing only applies to authorized deployments — a PUBLIC
        # (authorized=False) endpoint is free for everyone; charging only
        # the callers who happened to send a token would be both unfair and
        # trivially bypassed by dropping the header.
        priced_external = (external and pricing is not None
                           and pricing.enabled and stub.config.authorized)
        if stub.config.authorized and (ws is None or
                                       (external and not priced_external)):
            return web.json_response({"error": "unauthorized"}, status=401)
        if priced_external:
            return await self._serve_priced(request, stub, ws, pricing, tail)
        return await self._serve_stub(request, stub, tail)

    async def _serve_priced(self, request: web.Request, stub: Stub, ws,
                            pricing, tail: str) -> web.Response:
        """External pay-per-use call: gate on max_in_flight, serve, then
        bill the caller and credit the owner (usage.go TrackTaskCost)."""
        # in-flight tracking as timestamped entries, not a bare counter: a
        # crash-leaked entry expires individually (its deadline passes and
        # the next admission prunes it) without the counter-corruption a
        # whole-key TTL causes under continuous load. Deliberately
        # lock-free: concurrent racers can overshoot the cap by the number
        # of same-instant admissions — max_in_flight is a protective
        # bound, and a bounded transient overshoot beats serializing every
        # paid request through a store mutex (4 RTTs under contention).
        key = f"paid:inflight:{stub.stub_id}"
        req_entry = new_id("pr")
        deadline = time.time() + max(600.0, stub.config.timeout_s * 2)
        now_ts = time.time()
        entries = await self.store.hgetall(key) or {}
        stale = [k for k, v in entries.items() if float(v) <= now_ts]
        if stale:
            await self.store.hdel(key, *stale)
        if len(entries) - len(stale) >= max(1, pricing.max_in_flight):
            return web.json_response(
                {"error": "paid capacity exhausted, retry later"},
                status=429)
        await self.store.hset(key, req_entry, deadline)
        try:
            t0 = time.monotonic()
            resp = await self._serve_stub(request, stub, tail)
            duration_ms = (time.monotonic() - t0) * 1000.0
            if resp.status < 500:
                if pricing.cost_model == "duration":
                    cents = pricing.cost_per_task_duration_ms * duration_ms \
                        * 100.0
                else:
                    cents = pricing.cost_per_task * 100.0
                sid = stub.stub_id
                await self.usage.record_request(
                    ws.workspace_id, 1, metric=f"paid_tasks:{sid}")
                await self.usage.record_request(
                    ws.workspace_id, cents, metric=f"paid_cost_cents:{sid}")
                await self.usage.record_request(
                    stub.workspace_id, cents, metric=f"earned_cents:{sid}")
            return resp
        finally:
            await self.store.hdel(key, req_entry)

    async def _serve_stub(self, request: web.Request, stub: Stub,
                          tail: str) -> web.Response:
        if (stub.stub_type == StubType.REALTIME.value
                and request.headers.get("Upgrade", "").lower() == "websocket"):
            return await self._ws_proxy(stub, request)

        body = await request.read()
        # forward the full request surface (query string + end-to-end
        # headers) — ASGI apps depend on both; hop-by-hop headers stay
        path = "/" + tail if tail else "/"
        if request.query_string:
            path += f"?{request.query_string}"
        # NEVER forward the platform bearer token into a tenant container
        # (a priced/public endpoint's app would capture the CALLER'S
        # workspace credential); runners do no inbound auth of their own.
        # x-tpu9-trace is stripped too: the trace context is gateway-minted
        # below, never client-supplied (a forged header would parent a
        # tenant's engine spans under someone else's trace).
        # x-tpu9-budget-s / x-tpu9-request-id are gateway-level contracts
        # (ISSUE 15): the budget is re-emitted per attempt with spent time
        # deducted; the request id drives the idempotency journal here.
        skip_req = {"host", "connection", "transfer-encoding",
                    "content-length", "authorization", "x-tpu9-trace",
                    "x-tpu9-budget-s", "x-tpu9-request-id",
                    "x-tpu9-no-retry"}
        fwd_headers = [(k, v) for k, v in request.headers.items()
                       if k.lower() not in skip_req]

        # streaming relay (LLM token streams / SSE): the caller opts in via
        # Accept OR the JSON body's stream flag — both hops must agree, or
        # the runner would emit SSE that this proxy buffers whole
        wants_stream = "text/event-stream" in request.headers.get(
            "Accept", "")
        if not wants_stream and b'"stream"' in body[:4096]:
            try:
                wants_stream = bool(json.loads(body).get("stream"))
            except (ValueError, AttributeError):
                pass
        from . import survival as sv

        # request survivability context (ISSUE 15): one monotonic
        # deadline minted from the client's relative budget header, plus
        # the idempotency journal for client-supplied request ids
        ctx = sv.RequestContext.from_headers(request.headers)
        if ctx.expired():
            return web.json_response(
                {"error": "deadline_exceeded: budget exhausted at the "
                          "gateway"}, status=504)
        if ctx.request_id:
            dedup = await self._journal_gate(stub, ctx, stream=wants_stream)
            if dedup is not None:
                return dedup

        if wants_stream:
            return await self._serve_stub_stream(request, stub, path,
                                                 fwd_headers, body, ctx)
        try:
            return await self._serve_stub_buffered(request, stub, path,
                                                   fwd_headers, body, ctx)
        except BaseException:
            # an escaping exception/cancellation between journal-begin
            # and journal-finish must not strand the entry INFLIGHT (it
            # would 409 every retry of this id for the whole TTL);
            # finish(500) CLEARS it so the retry executes afresh. Guarded
            # by journal_closed: a cancellation AFTER the terminal write
            # (client already disconnected to retry) must not delete the
            # DONE entry — that would re-open the double-execution hole
            if ctx.request_id and not ctx.journal_closed:
                try:
                    await self.journal.finish(
                        stub.workspace_id, ctx.request_id, 500,
                        stub_id=stub.stub_id)
                except Exception:   # noqa: BLE001 — best-effort cleanup
                    pass
            raise

    async def _serve_stub_buffered(self, request: web.Request, stub: Stub,
                                   path: str, fwd_headers: list,
                                   body: bytes, ctx) -> web.Response:
        from . import survival as sv
        from ..observability import tracer
        from ..utils.backoff import BackoffPolicy
        rcfg = self.cfg.router
        # X-Tpu9-No-Retry: client opt-out for non-idempotent handlers —
        # at-most-once dispatch, failures surface verbatim
        attempts = 1 if (self.fleet_router is None
                         or request.headers.get(sv.NO_RETRY_HEADER)) \
            else rcfg.failover_max_attempts
        budget = sv.FailoverBudget(
            attempts,
            BackoffPolicy(base_s=rcfg.failover_backoff_base_s,
                          max_s=rcfg.failover_backoff_max_s),
            deadline_mono=ctx.deadline_mono)
        with tracer.span("gateway.invoke",
                         attrs={"stub_id": stub.stub_id,
                                "workspace_id": stub.workspace_id,
                                "method": request.method}) as sp:
            # propagate the span context across the runner RPC boundary:
            # the llm runner parses this header and the engine records its
            # prefill/decode-window spans under the SAME trace id, shipped
            # back on the pressure heartbeat (ISSUE 8)
            trace_hdr = ("X-Tpu9-Trace", f"{sp.trace_id}:{sp.span_id}")
            if self.fleet_router is not None:
                # fleet front door: fair-queue by the CALLING tenant (a
                # priced endpoint's external callers compete with each
                # other, not under the owner's lane), place by KV
                # affinity, shed with 429/503 + Retry-After
                caller = request.get("workspace")
                tenant = caller.workspace_id if caller else stub.workspace_id

                async def _attempt(attempt: int, avoid: set):
                    hdrs = list(fwd_headers) + [trace_hdr]
                    rem = ctx.remaining_s()
                    if rem is not None:
                        # spent budget is DEDUCTED across attempts —
                        # the replica sees what is actually left
                        hdrs.append((sv.BUDGET_HEADER, f"{rem:.3f}"))

                    async def _fwd(prefer):
                        return await self.endpoints.forward(
                            stub, request.method, path, hdrs, body,
                            prefer=prefer, avoid=avoid or None)

                    return await self.fleet_router.submit(
                        stub, tenant, body, _fwd,
                        deadline_mono=ctx.deadline_mono)

                def _on_failover(attempt, failed, delay):
                    # automatic failover (ISSUE 15): counter + a span on
                    # the request's existing trace tree; the failed
                    # replica's affinity entries drop so repeat prefixes
                    # re-home now
                    self.fleet_router.signals.failover(
                        stub.stub_id, reason=f"http_{failed.status}")
                    if failed.container_id:
                        self.fleet_router.note_dispatch_failure(
                            failed.container_id)
                    now_m = time.monotonic()
                    tracer.record_span(
                        "gateway.failover", sp.trace_id, sp.span_id,
                        time.time(), now_m,
                        attrs={"stub_id": stub.stub_id,
                               "workspace_id": stub.workspace_id,
                               "attempt": attempt,
                               "failed_status": failed.status,
                               "failed_replica": failed.container_id or "",
                               "backoff_s": round(delay, 4)},
                        end_mono=now_m)

                result = await sv.submit_with_failover(
                    _attempt, budget, on_failover=_on_failover)
                if budget.attempt > 1:
                    self.fleet_router.signals.retry_result(
                        stub.stub_id, recovered=result.status < 400)
            else:
                hdrs = list(fwd_headers) + [trace_hdr]
                rem = ctx.remaining_s()
                if rem is not None:
                    hdrs.append((sv.BUDGET_HEADER, f"{rem:.3f}"))
                result = await self.endpoints.forward(stub, request.method,
                                                      path, hdrs,
                                                      body)
            sp.attrs["status"] = result.status
            if budget.attempt > 1:
                sp.attrs["attempts"] = budget.attempt
        if ctx.request_id:
            ctype = next((v for k, v in result.headers
                          if k.lower() == "content-type"), "")
            await self.journal.finish(stub.workspace_id, ctx.request_id,
                                      result.status, result.body,
                                      attempts=budget.attempt,
                                      stub_id=stub.stub_id,
                                      content_type=ctype)
            ctx.journal_closed = True
        await self.usage.record_request(stub.workspace_id)
        # preserve the container's response headers (ASGI apps set their own
        # content types and custom headers, incl. duplicates like
        # Set-Cookie); drop hop-by-hop ones. content-encoding excluded: the
        # buffer's client session already decompressed the body.
        resp = web.Response(status=result.status, body=result.body)
        skip = {"connection", "transfer-encoding", "content-length", "server",
                "date", "content-encoding"}
        for k, v in result.headers:
            if k.lower() not in skip:
                resp.headers.add(k, v)
        resp.headers.setdefault("Content-Type", "application/json")
        return resp

    async def _journal_gate(self, stub: Stub, ctx,
                            stream: bool = False) -> Optional[web.Response]:
        """Idempotency gate for client-supplied request ids (ISSUE 15):
        None = this caller owns execution; otherwise the dedup response.
        A retry of an IN-FLIGHT request gets 409 + Retry-After instead of
        a second execution; a retry of a COMPLETED one gets the stored
        result replayed (buffered) or a completion summary (streams)."""
        from . import survival as sv
        state, rec = await self.journal.begin(stub.workspace_id,
                                              ctx.request_id,
                                              stub_id=stub.stub_id)
        if state == sv.NEW:
            return None
        if state == sv.INFLIGHT:
            resp = web.json_response(
                {"error": "request already in flight (idempotent retry "
                          "refused — the original attempt is still "
                          "executing)",
                 "request_id": ctx.request_id,
                 "watermark": rec.get("watermark", 0),
                 "attempts": rec.get("attempts", 1)}, status=409)
            resp.headers["Retry-After"] = "1"
            return resp
        body = sv.RequestJournal.replay_body(rec)
        if body is not None and not stream:
            resp = web.Response(status=int(rec.get("status", 200)),
                                body=body,
                                content_type=str(rec.get("ctype", "")
                                                 or "application/json"))
            resp.headers[sv.REPLAY_HEADER] = "1"
            return resp
        resp = web.json_response(
            {"error": "request already completed",
             "request_id": ctx.request_id,
             "status": rec.get("status", 200),
             "tokens_delivered": rec.get("watermark", 0),
             "attempts": rec.get("attempts", 1)}, status=409)
        resp.headers[sv.REPLAY_HEADER] = "1"
        return resp

    async def _serve_stub_stream(self, request: web.Request, stub: Stub,
                                 path: str, fwd_headers: list,
                                 body: bytes, ctx) -> web.StreamResponse:
        # ctx is REQUIRED: re-minting it from headers here would restart
        # the monotonic deadline at 'now' and silently grant the full
        # budget again — the opposite of the deduction invariant
        try:
            return await self._serve_stub_stream_inner(
                request, stub, path, fwd_headers, body, ctx)
        except BaseException:
            # same journal hygiene as the buffered path: an escaping
            # exception must not strand the entry INFLIGHT for the TTL
            # (journal_closed: never delete a terminal write)
            if ctx.request_id and not ctx.journal_closed:
                try:
                    await self.journal.finish(
                        stub.workspace_id, ctx.request_id, 500,
                        stub_id=stub.stub_id)
                except Exception:   # noqa: BLE001 — best-effort cleanup
                    pass
            raise

    async def _serve_stub_stream_inner(self, request: web.Request,
                                       stub: Stub, path: str,
                                       fwd_headers: list, body: bytes,
                                       ctx) -> web.StreamResponse:
        """Incremental relay: container chunks reach the client as they
        are produced (buffer.go:666's streaming proxy role). Used for LLM
        token streams — a buffered proxy would hold every token until the
        generation finished.

        Survivability (ISSUE 15): for LLM token-stream bodies the relay
        parses the SSE events it forwards and keeps the token watermark;
        when the serving replica dies or stalls mid-generation, the
        stream RESUMES on a healthy replica by replaying
        ``prompt + delivered`` as a fresh prefill with the budget reduced
        by the watermark — the client sees one seamless, duplicate-free
        token sequence. Non-LLM streams keep the legacy single-attempt
        relay (there is no watermark to splice on)."""
        import aiohttp as _aiohttp

        from ..abstractions.common.buffer import ForwardResult
        from ..observability import tracer
        from ..observability.decisions import ledger, rej
        from ..utils.backoff import BackoffPolicy
        from . import survival as sv

        rcfg = self.cfg.router
        llm = sv.parse_llm_stream_body(body) \
            if self.fleet_router is not None else None
        resume = sv.StreamResumption(llm["prompt"], llm["max_new"],
                                     llm["payload"]) if llm else None
        # kvwire block shipping (ISSUE 16): ask the serving replica to
        # export its prefill KV — the kv_key announcement primes O(1)
        # failover resume (and the disagg decode handoff reuses the same
        # request mode). TPU9_KV_SHIP=0/1 overrides for chaos runs.
        ship_env = os.environ.get("TPU9_KV_SHIP", "")
        if (resume is not None and not llm["payload"].get("adopt_kv")
                and len(llm["prompt"]) >= rcfg.kv_ship_min_tokens
                and (ship_env == "1" if ship_env
                     else rcfg.kv_ship_enabled)):
            body = json.dumps({**llm["payload"], "kv_export": True,
                               "stream": True}).encode()
        # prefix-directory peer adopt (ISSUE 20): when the directory says
        # this body's longest prefix lives ONLY in the peer cache (its
        # last serving replica is gone — scale-to-zero, death), hand the
        # chosen replica the adopt hint so it pulls the tier instead of
        # recomputing. Reuses the ISSUE 15 adopt_kv splice path verbatim;
        # the hint is advisory — a lost peer entry degrades to prefill.
        if (llm is not None and not llm["payload"].get("adopt_kv")
                and self.fleet_router is not None):
            adopt = self.fleet_router.kv_adopt_hint(body)
            if adopt is not None:
                payload = json.loads(body)
                payload["adopt_kv"] = adopt
                body = json.dumps(payload).encode()
        budget = sv.FailoverBudget(
            rcfg.failover_max_attempts
            if (resume is not None
                and not request.headers.get(sv.NO_RETRY_HEADER)) else 1,
            BackoffPolicy(base_s=rcfg.failover_backoff_base_s,
                          max_s=rcfg.failover_backoff_max_s),
            deadline_mono=ctx.deadline_mono)
        caller = request.get("workspace")
        tenant = caller.workspace_id if caller else stub.workspace_id
        avoid: set = set()
        sr: Optional[web.StreamResponse] = None
        trace_ref = ["", ""]           # [trace_id, span_id] for failover
        finished = False
        terminal_error = False         # stream ended on a forwarded error
        last_failure: Optional[sv.AttemptOutcome] = None

        async def _finish_journal(status: int) -> None:
            if ctx.request_id:
                await self.journal.finish(
                    stub.workspace_id, ctx.request_id, status,
                    watermark=resume.watermark if resume else 0,
                    attempts=budget.attempt, stub_id=stub.stub_id)
                ctx.journal_closed = True

        async def _client_error(status: int, payload: dict,
                                headers=()) -> web.StreamResponse:
            """Terminal failure: plain response if nothing was sent yet,
            else an SSE error event on the already-prepared stream."""
            await _finish_journal(status)
            if sr is None:
                resp = web.json_response(payload, status=status)
                for k, v in headers:
                    resp.headers[k] = v
                return resp
            try:
                await sr.write(
                    f"data: {json.dumps(payload)}\n\n".encode())
                await sr.write_eof()
            except (ConnectionResetError, OSError) as exc:
                log.debug("client gone during stream error: %s", exc)
            return sr

        while True:
            # all owed tokens already delivered — or the generation
            # visibly ENDED (client-declared eos_id as the last token) —
            # but the terminal event was lost with the replica:
            # synthesize completion, no replay (replaying past EOS would
            # mint tokens the unfailed stream never produces)
            if resume is not None and budget.attempt > 1 \
                    and (resume.remaining == 0 or resume.ended_on_eos):
                ledger.record(
                    "failover", "resume_mode", request_id=trace_ref[0],
                    chosen="synthesize_done",
                    rejected=[rej("replay", "all_tokens_delivered"
                                  if resume.remaining == 0
                                  else "ended_on_eos")],
                    signals={"watermark": resume.watermark,
                             "attempt": budget.attempt},
                    stub_id=stub.stub_id, workspace_id=stub.workspace_id)
                finished = True
                break
            if resume is not None and budget.attempt > 1:
                attempt_body = resume.resume_payload()
                # the ship-vs-reprefill outcome (ISSUE 19): did this
                # resume splice shipped KV blocks or pay a re-prefill?
                ledger.record(
                    "failover", "resume_mode", request_id=trace_ref[0],
                    chosen="block_ship" if resume.kv_key else "re_prefill",
                    rejected=[] if resume.kv_key
                    else [rej("block_ship", "no_kv_key_announced")],
                    signals={"watermark": resume.watermark,
                             "remaining": resume.remaining,
                             "kv_tokens": resume.kv_tokens,
                             "attempt": budget.attempt},
                    stub_id=stub.stub_id, workspace_id=stub.workspace_id)
            else:
                attempt_body = body
            hdrs = list(fwd_headers)
            rem = ctx.remaining_s()
            if rem is not None:
                if rem <= 0:
                    return await _client_error(
                        504, {"error": "deadline_exceeded: budget "
                                       "exhausted at the gateway"})
                hdrs.append((sv.BUDGET_HEADER, f"{rem:.3f}"))

            if budget.attempt == 1:
                # the stream-setup span covers admission + placement +
                # connect (the TTFT-shaped part a stream's caller feels);
                # the relay loop stays OUTSIDE — a span held open for a
                # minutes-long stream would only reach the ring at close.
                # Resume attempts parent onto this same context.
                span_cm = tracer.span("gateway.invoke",
                                      attrs={"stub_id": stub.stub_id,
                                             "workspace_id":
                                             stub.workspace_id,
                                             "method": request.method,
                                             "stream": True})
            else:
                span_cm = None
            sp = span_cm.__enter__() if span_cm is not None else None
            try:
                if sp is not None:
                    trace_ref[0], trace_ref[1] = sp.trace_id, sp.span_id
                hdrs.append(("X-Tpu9-Trace",
                             f"{trace_ref[0]}:{trace_ref[1]}"))
                prefer: list = []
                if self.fleet_router is not None:
                    # streams skip the fair queue (a token stream holds
                    # its replica for minutes) but still shed at the door
                    # and carry the router's affinity preference; their
                    # budget slot rides the handle's lifetime via on_close
                    shed, prefer = await self.fleet_router.admit_stream(
                        stub, tenant, attempt_body,
                        deadline_mono=ctx.deadline_mono)
                    if shed is not None:
                        # usage records for sheds on BOTH paths: metrics/
                        # billing must not diverge between buffered and
                        # streaming for identical client behavior (first
                        # attempt only — failover re-admissions are
                        # gateway-initiated, not billable)
                        if budget.attempt == 1:
                            await self.usage.record_request(
                                stub.workspace_id)
                        if sp is not None:
                            sp.attrs["status"] = shed.status
                        return await _client_error(
                            shed.status, json.loads(shed.body),
                            headers=shed.headers)
                handle = await self.endpoints.forward_stream(
                    stub, request.method, path, hdrs, attempt_body,
                    prefer=prefer, avoid=avoid or None,
                    # the per-chunk gap bound only applies to RESUMABLE
                    # streams — the relay recovers from the timeout; a
                    # legacy stream keeps the full request budget so a
                    # legitimately quiet app is never truncated
                    gap_s=rcfg.stream_gap_s if resume is not None
                    else None)
                if sp is not None:
                    sp.attrs["status"] = getattr(handle, "status", 0)
            finally:
                if span_cm is not None:
                    span_cm.__exit__(None, None, None)
            # usage records ONCE per client request (first attempt) —
            # gateway-initiated failover attempts must not inflate the
            # tenant's billing (the buffered path bills once too)
            if budget.attempt == 1:
                await self.usage.record_request(stub.workspace_id)

            if isinstance(handle, ForwardResult):
                failed = sv.AttemptOutcome(
                    kind="failed", reason=f"connect_{handle.status}",
                    replica=handle.container_id, error_body=handle.body)
                verdict = sv.classify_result(handle.status, handle.body)
            elif handle.status >= 400:
                # connected but the replica refused (engine dead → 500,
                # booting → 503): drain the small error body for the
                # classifier, then treat like a connect failure
                err = b""
                try:
                    async for chunk in handle.iter_chunks():
                        err += chunk
                        if len(err) > 4096:
                            break
                except (ConnectionResetError, OSError, _aiohttp.ClientError,
                        asyncio.TimeoutError):
                    pass
                await handle.close()
                failed = sv.AttemptOutcome(
                    kind="failed", reason=f"http_{handle.status}",
                    replica=handle.container_id, error_body=err)
                verdict = sv.classify_result(handle.status, err)
            else:
                if self.fleet_router is not None and handle.container_id:
                    handle.on_close = self.fleet_router.stream_started(
                        stub, attempt_body, handle.container_id)
                if resume is None:
                    # legacy verbatim relay (non-LLM streams): single
                    # attempt, bytes forwarded untouched. The journal
                    # entry still closes — leaving it INFLIGHT would
                    # 409 every retry of this id for the whole TTL
                    out = await self._relay_stream_legacy(request, handle)
                    await _finish_journal(getattr(handle, "status", 200))
                    return out
                if sr is None:
                    sr = web.StreamResponse(status=handle.status)
                    skip = {"connection", "transfer-encoding",
                            "content-length", "server", "date",
                            "content-encoding"}
                    for k, v in handle.headers:
                        if k.lower() not in skip:
                            sr.headers.add(k, v)
                    try:
                        await sr.prepare(request)
                    except (ConnectionResetError, OSError) as exc:
                        log.debug("client gone before stream start: %s",
                                  exc)
                        await handle.close()
                        await _finish_journal(499)
                        return sr
                outcome = await self._relay_stream_events(
                    handle, resume, sr)
                await handle.close()
                if outcome.kind == "done":
                    finished = True
                    terminal_error = outcome.reason == "error_event"
                    break
                if outcome.kind == "client_gone":
                    await _finish_journal(499)
                    return sr
                failed = outcome
                verdict = sv.RETRYABLE

            # ---- failover decision -------------------------------------
            last_failure = failed
            budget.note_failure()
            delay = budget.next_delay() if verdict == sv.RETRYABLE else None
            if delay is None:
                ledger.record(
                    "failover",
                    "final" if verdict != sv.RETRYABLE else "give_up",
                    request_id=trace_ref[0], chosen="return_error",
                    rejected=[rej("retry", f"verdict:{verdict}"
                                  if verdict != sv.RETRYABLE
                                  else "budget_exhausted")],
                    signals={"reason": failed.reason,
                             "attempt": budget.attempt,
                             "max_attempts": budget.max_attempts,
                             "watermark": resume.watermark if resume
                             else 0},
                    stub_id=stub.stub_id, workspace_id=stub.workspace_id)
                if self.fleet_router is not None and budget.attempt > 1:
                    self.fleet_router.signals.retry_result(
                        stub.stub_id, recovered=False)
                status = 502 if failed.kind == "failed" else 500
                if failed.reason.startswith(("connect_", "http_")):
                    try:
                        status = int(failed.reason.split("_", 1)[1])
                    except ValueError:
                        pass
                payload = None
                if failed.error_body:
                    try:
                        payload = json.loads(failed.error_body)
                    except ValueError:
                        payload = {"error": failed.error_body.decode(
                            errors="replace")[:500]}
                if verdict != sv.RETRYABLE and payload is not None:
                    # non-retryable upstream error (request shape, app
                    # 4xx): forward the ORIGINAL status + body verbatim
                    # — the legacy relay's contract; a generic
                    # "failover exhausted" message here would bury the
                    # actual diagnostic
                    return await _client_error(status, payload)
                out_payload = {
                    "error": "stream failed and failover budget "
                             f"exhausted ({failed.reason})",
                    "attempts": budget.attempt,
                    "tokens_delivered": resume.watermark
                    if resume else 0}
                if payload is not None:
                    out_payload["last_error"] = payload.get(
                        "error", payload) if isinstance(payload, dict) \
                        else payload
                return await _client_error(status, out_payload)
            if failed.replica:
                avoid.add(failed.replica)
            if self.fleet_router is not None:
                self.fleet_router.signals.failover(stub.stub_id,
                                                   reason=failed.reason)
                if failed.replica:
                    self.fleet_router.note_dispatch_failure(failed.replica)
            if trace_ref[0]:
                now_m = time.monotonic()
                tracer.record_span(
                    "gateway.failover", trace_ref[0], trace_ref[1],
                    time.time(), now_m,
                    attrs={"stub_id": stub.stub_id,
                           "workspace_id": stub.workspace_id,
                           "attempt": budget.attempt,
                           "reason": failed.reason,
                           "failed_replica": failed.replica,
                           "watermark": resume.watermark if resume else 0,
                           "backoff_s": round(delay, 4)},
                    end_mono=now_m)
            # next_delay() consumed the retry: budget.attempt is the one
            # about to run — the record mirrors survival's buffered path
            ledger.record(
                "failover", "retry", request_id=trace_ref[0],
                chosen=f"attempt_{budget.attempt}",
                rejected=[rej(failed.replica or "replica", failed.reason)],
                signals={"verdict": verdict,
                         "failed_attempt": budget.attempt - 1,
                         "max_attempts": budget.max_attempts,
                         "watermark": resume.watermark if resume else 0,
                         "kv_key_known": bool(resume and resume.kv_key),
                         "backoff_s": round(delay, 4)},
                stub_id=stub.stub_id, workspace_id=stub.workspace_id)
            if ctx.request_id and resume is not None:
                await self.journal.update(stub.workspace_id,
                                          ctx.request_id,
                                          resume.watermark, budget.attempt,
                                          stub_id=stub.stub_id)
            log.warning(
                "stream failover for %s: attempt %d, reason=%s, "
                "watermark=%d, replica=%s", stub.stub_id, budget.attempt,
                failed.reason, resume.watermark if resume else 0,
                failed.replica or "?")
            await asyncio.sleep(delay)

        # ---- terminal: one seamless done event (or the forwarded error) --
        if self.fleet_router is not None and budget.attempt > 1:
            self.fleet_router.signals.retry_result(
                stub.stub_id, recovered=not terminal_error)
        # an error-terminal stream (deadline/app error forwarded to the
        # client) must not journal as a completed 200 — finish(500)
        # clears the entry so a retry with this id executes afresh
        await _finish_journal(500 if terminal_error else 200)
        if sr is None:
            # finished before anything streamed (resume.remaining == 0 on
            # a zero-attempt splice) — degenerate but possible
            sr = web.StreamResponse(status=200)
            sr.headers["Content-Type"] = "text/event-stream"
            try:
                await sr.prepare(request)
            except (ConnectionResetError, OSError):
                return sr
        try:
            if resume is not None and finished and not terminal_error:
                await sr.write(
                    f"data: {json.dumps(resume.done_event())}\n\n"
                    .encode())
            await sr.write_eof()
        except (ConnectionResetError, OSError) as exc:
            log.debug("client gone at stream end: %s", exc)
        return sr

    async def _relay_stream_legacy(self, request: web.Request,
                                   handle) -> web.StreamResponse:
        """Pre-ISSUE-15 verbatim relay for non-resumable streams."""
        import aiohttp as _aiohttp
        sr = web.StreamResponse(status=handle.status)
        skip = {"connection", "transfer-encoding", "content-length",
                "server", "date", "content-encoding"}
        for k, v in handle.headers:
            if k.lower() not in skip:
                sr.headers.add(k, v)
        try:
            await sr.prepare(request)
            async for chunk in handle.iter_chunks():
                await sr.write(chunk)
            await sr.write_eof()
        except (ConnectionResetError, OSError, _aiohttp.ClientError,
                asyncio.TimeoutError) as exc:
            # client went away OR the container died / stalled mid-stream:
            # the prepared response can only be dropped, not rewritten —
            # but it must not escape as an unhandled handler exception
            log.debug("stream relay ended early: %s", exc)
        finally:
            await handle.close()
        return sr

    async def _relay_stream_events(self, handle, resume,
                                   sr: web.StreamResponse):
        """Event-aware relay for one attempt of a resumable LLM stream:
        forward token events (advancing the watermark), swallow the
        attempt's own done/error events (the terminal event is owned by
        the failover loop — a resumed attempt's done only knows its own
        suffix), and classify how the attempt ended."""
        import aiohttp as _aiohttp
        from . import survival as sv
        parser = sv.SseParser()
        it = handle.iter_chunks().__aiter__()
        while True:
            try:
                chunk = await it.__anext__()
            except StopAsyncIteration:
                # upstream closed without a terminal event: the replica
                # (or its runner process) died mid-stream
                return sv.AttemptOutcome(kind="failed",
                                         reason="stream_eof",
                                         replica=handle.container_id)
            except asyncio.TimeoutError:
                return sv.AttemptOutcome(kind="failed",
                                         reason="stream_gap",
                                         replica=handle.container_id)
            except (ConnectionResetError, OSError,
                    _aiohttp.ClientError) as exc:
                return sv.AttemptOutcome(
                    kind="failed", reason=f"transport_"
                    f"{type(exc).__name__}", replica=handle.container_id)
            for ev in parser.feed(chunk):
                if "token" in ev:
                    resume.note_token(ev["token"])
                    try:
                        await sr.write(
                            f"data: {json.dumps({'token': ev['token']})}"
                            "\n\n".encode())
                    except (ConnectionResetError, OSError) as exc:
                        log.debug("client gone mid-stream: %s", exc)
                        return sv.AttemptOutcome(kind="client_gone")
                elif "kv_key" in ev:
                    # kvwire announcement (ISSUE 16): the exporting
                    # replica published this stream's KV blocks —
                    # remember the key for block-ship resume, never
                    # forward transport bookkeeping to the client
                    resume.note_kv(str(ev.get("kv_key", "")),
                                   int(ev.get("n_tokens", 0) or 0))
                elif ev.get("done"):
                    return sv.AttemptOutcome(kind="done")
                elif "error" in ev:
                    msg = str(ev.get("error", ""))
                    if sv.classify_result(
                            500, msg.encode()) == sv.RETRYABLE:
                        return sv.AttemptOutcome(
                            kind="failed", reason="engine_error",
                            replica=handle.container_id,
                            error_body=msg.encode())
                    # non-retryable engine error (deadline, request
                    # shape): surface it verbatim and end the stream
                    try:
                        await sr.write(
                            f"data: {json.dumps(ev)}\n\n".encode())
                    except (ConnectionResetError, OSError):
                        return sv.AttemptOutcome(kind="client_gone")
                    return sv.AttemptOutcome(kind="done",
                                             reason="error_event")
                else:
                    # unknown/raw frame: forward untouched
                    raw = ev.get("_raw")
                    out = raw + b"\n\n" if raw else \
                        f"data: {json.dumps(ev)}\n\n".encode()
                    try:
                        await sr.write(out)
                    except (ConnectionResetError, OSError):
                        return sv.AttemptOutcome(kind="client_gone")

    async def _ws_proxy(self, stub: Stub, request: web.Request) -> web.StreamResponse:
        """Bidirectional websocket proxy for @realtime deployments
        (endpoint/buffer.go:644 equivalent). Holds a concurrency token on the
        chosen container for the socket's lifetime."""
        import aiohttp as _aiohttp

        inst = await self.endpoints.get_or_create_instance(stub)
        # demand is held for the WHOLE session: it both triggers
        # scale-from-zero and prevents keep-warm scale-down from killing the
        # serving container while the socket is open
        with inst.buffer.hold_demand():
            target = await inst.buffer.acquire(
                deadline_s=min(stub.config.timeout_s, 30.0))
            if target is None:
                return web.json_response({"error": "no capacity"}, status=503)
            container_id, address = target

            ws_client = web.WebSocketResponse()
            try:
                await ws_client.prepare(request)
                if self._proxy_session is None or self._proxy_session.closed:
                    self._proxy_session = _aiohttp.ClientSession()
                async with self._proxy_session.ws_connect(
                        f"http://{address}/",
                        # bounds the websocket CLOSE handshake (TMO001);
                        # the session itself is deliberately unbounded —
                        # realtime sockets live for hours
                        timeout=_aiohttp.ClientWSTimeout(
                            ws_close=self.cfg.router.rpc_timeout_s)
                        ) as ws_upstream:

                    async def pump_up():
                        async for msg in ws_client:
                            if msg.type == web.WSMsgType.TEXT:
                                await ws_upstream.send_str(msg.data)
                            elif msg.type == web.WSMsgType.BINARY:
                                await ws_upstream.send_bytes(msg.data)
                        await ws_upstream.close()

                    async def pump_down():
                        async for msg in ws_upstream:
                            if msg.type == _aiohttp.WSMsgType.TEXT:
                                await ws_client.send_str(msg.data)
                            elif msg.type == _aiohttp.WSMsgType.BINARY:
                                await ws_client.send_bytes(msg.data)
                        await ws_client.close()

                    await asyncio.gather(pump_up(), pump_down(),
                                         return_exceptions=True)
            finally:
                await self.containers.release_request_token(stub.stub_id,
                                                            container_id)
        return ws_client

    # -- handlers: REST v1 ----------------------------------------------------

    async def _list_deployments(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        deps = await self.backend.list_deployments(ws.workspace_id)
        return web.json_response([d.to_dict() for d in deps])

    async def _delete_deployment(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        dep = await self.backend.get_deployment_by_id(request.match_info["id"])
        if dep is None or dep.workspace_id != ws.workspace_id:
            return web.json_response({"error": "not found"}, status=404)
        await self.backend.set_deployment_active(dep.deployment_id, False)
        await self.endpoints.drain_stub(dep.stub_id)
        return web.json_response({"ok": True})

    # -- concurrency limits + apps -------------------------------------------

    # -- workspaces ----------------------------------------------------------

    async def _workspace_create(self, request: web.Request) -> web.Response:
        """Operator mints a workspace + its first token (reference
        /api/v1/workspace)."""
        self._require_operator(request)
        data = await request.json()
        name = data.get("name", "")
        if not name:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "name required"}),
                content_type="application/json")
        if await self.backend.get_workspace_by_name(name) is not None:
            raise web.HTTPConflict(
                text=json.dumps({"error": f"workspace {name!r} exists"}),
                content_type="application/json")
        ws = await self.backend.create_workspace(name)
        tok = await self.backend.create_token(ws.workspace_id)
        return web.json_response({"workspace_id": ws.workspace_id,
                                  "name": ws.name, "token": tok.key})

    async def _workspace_token(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        workspace_id = request.match_info["workspace_id"]
        if await self.backend.get_workspace(workspace_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "workspace not found"}),
                content_type="application/json")
        tok = await self.backend.create_token(workspace_id)
        return web.json_response({"token": tok.key,
                                  "token_id": tok.token_id})

    # -- tokens (self-service; reference /api/v1/token) ----------------------

    def _require_user_token(self, request: web.Request):
        """Token management is for WORKSPACE tokens only. Runner tokens ride
        inside user-controlled containers (build steps, handlers) — letting
        one mint a durable workspace key or revoke the owner's tokens would
        be privilege escalation."""
        if request.get("token_type") != "workspace":
            raise web.HTTPForbidden(
                text=json.dumps({"error": "workspace token required"}),
                content_type="application/json")

    async def _token_list(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        self._require_user_token(request)
        out = []
        for t in await self.backend.list_tokens(ws.workspace_id):
            out.append({"token_id": t.token_id,
                        "key_prefix": t.key[:8],     # never the full key
                        "token_type": t.token_type,
                        "active": t.active,
                        "created_at": t.created_at})
        return web.json_response(out)

    async def _token_create(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        self._require_user_token(request)
        tok = await self.backend.create_token(ws.workspace_id)
        # the ONLY response carrying the full key
        return web.json_response({"token_id": tok.token_id,
                                  "token": tok.key})

    async def _token_revoke(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        self._require_user_token(request)
        token_id = request.match_info["token_id"]
        mine = {t.token_id for t in
                await self.backend.list_tokens(ws.workspace_id)}
        if token_id not in mine:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "token not found"}),
                content_type="application/json")
        return web.json_response(
            {"ok": await self.backend.revoke_token(token_id)})

    # -- machines (BYOC agents; reference pkg/agent + machine API) -----------

    async def _machine_create(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        data = await request.json()
        if not data.get("name"):
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "name required"}),
                content_type="application/json")
        m = await self.backend.create_machine(
            data["name"], data.get("pool", "default"),
            max_workers=int(data.get("max_workers", 1)))
        # the ONLY response that carries the join token — it is one-time
        return web.json_response(m)

    async def _machine_list(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        out = []
        for m in await self.backend.list_machines(
                request.query.get("pool", "")):
            m.pop("join_token", None)
            try:
                m["preflight"] = json.loads(m.get("preflight") or "[]")
            except ValueError:
                m["preflight"] = []
            hb = await self.store.get(Keys.machine_heartbeat(m["machine_id"]))
            m["alive"] = hb is not None
            m["telemetry"] = hb or {}
            m["desired_workers"] = int(
                await self.store.get(
                    Keys.machine_desired(m["machine_id"])) or 0)
            out.append(m)
        return web.json_response(out)

    async def _machine_delete(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        machine_id = request.match_info["machine_id"]
        await self.store.delete(Keys.machine_desired(machine_id),
                                Keys.machine_heartbeat(machine_id),
                                Keys.machine_logs(machine_id))
        return web.json_response(
            {"ok": await self.backend.delete_machine(machine_id)})

    async def _machine_join(self, request: web.Request) -> web.Response:
        data = await request.json()
        m = await self.backend.register_machine(
            data.get("token", ""), data.get("hostname", ""),
            int(data.get("cpu_millicores", 0)),
            int(data.get("memory_mb", 0)),
            int(data.get("tpu_chips", 0)),
            data.get("tpu_generation", ""),
            hourly_cost_micros=int(data.get("hourly_cost_micros", 0)),
            reliability=float(data.get("reliability", 1.0)),
            preflight=self._bounded_preflight(data.get("preflight", [])))
        if m is None:
            # invalid OR already-consumed token — indistinguishable on
            # purpose (don't confirm which tokens once existed)
            raise web.HTTPForbidden(
                text=json.dumps({"error": "invalid join token"}),
                content_type="application/json")
        # the ACTUAL bound port, not the configured one — state_port may be
        # -1 ("any free port") and an agent can't dial 'host:-1'
        state_port = (self.state_server.port if self.state_server
                      else self.cfg.gateway.state_port)
        return web.json_response({
            "machine_id": m["machine_id"],
            "pool": m["pool"],
            "max_workers": m["max_workers"],
            "worker_token": self.worker_token,
            "state_port": state_port,
            "state_auth_token": self.cfg.database.state_auth_token,
        })

    def _machine_for_worker(self, request: web.Request) -> str:
        if not request.get("is_worker"):
            raise web.HTTPForbidden(
                text=json.dumps({"error": "worker token required"}),
                content_type="application/json")
        return request.match_info["machine_id"]

    async def _machine_desired(self, request: web.Request) -> web.Response:
        machine_id = self._machine_for_worker(request)
        if await self.backend.get_machine(machine_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "machine not found"}),
                content_type="application/json")
        n = int(await self.store.get(Keys.machine_desired(machine_id)) or 0)
        return web.json_response({"workers": n})

    async def _machine_heartbeat(self, request: web.Request) -> web.Response:
        machine_id = self._machine_for_worker(request)
        if await self.backend.get_machine(machine_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "machine not found"}),
                content_type="application/json")
        data = await request.json()
        await self.backend.touch_machine(machine_id)
        await self.store.set(Keys.machine_heartbeat(machine_id),
                             {"ts": time.time(), **data}, ttl=60.0)
        return web.json_response({"ok": True})

    async def _machine_release(self, request: web.Request) -> web.Response:
        """Agent reports voluntary worker exits (idle spindown, rc=0): the
        desired count drops so the agent doesn't respawn forever what the
        platform deliberately shut down."""
        machine_id = self._machine_for_worker(request)
        if await self.backend.get_machine(machine_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "machine not found"}),
                content_type="application/json")
        data = await request.json()
        n = max(1, int(data.get("count", 1)))
        left = await self.store.incr(Keys.machine_desired(machine_id),
                                     by=-n, floor=0)
        return web.json_response({"workers": left})

    MACHINE_LOG_CAP = 5000            # per-machine tail kept in the store

    @staticmethod
    def _bounded_preflight(report) -> str:
        """Serialize the agent's preflight report bounded per FIELD (≤32
        checks, 64-char names, 256-char details ⇒ ≤ ~12 KB total) — never
        by slicing the serialized string mid-token, which machine-list
        would silently read back as []."""
        if not isinstance(report, list):
            return "[]"
        return json.dumps(
            [{"name": str(c.get("name", ""))[:64],
              "ok": bool(c.get("ok")),
              "critical": bool(c.get("critical")),
              "detail": str(c.get("detail", ""))[:256]}
             for c in report[:32] if isinstance(c, dict)])

    async def _machine_logs_push(self, request: web.Request) -> web.Response:
        machine_id = self._machine_for_worker(request)
        if await self.backend.get_machine(machine_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "machine not found"}),
                content_type="application/json")
        data = await request.json()
        lines = [str(ln)[:4096] for ln in data.get("lines", [])][:1000]
        if lines:
            key = Keys.machine_logs(machine_id)
            await self.store.rpush(key, *lines)
            # capped tail in ONE store call (not N lpop round-trips)
            await self.store.ltrim(key, -self.MACHINE_LOG_CAP, -1)
        return web.json_response({"ok": True, "accepted": len(lines)})

    async def _machine_logs_get(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        machine_id = request.match_info["machine_id"]
        if await self.backend.get_machine(machine_id) is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "machine not found"}),
                content_type="application/json")
        try:
            tail = int(request.query.get("tail", 200))
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "tail must be an integer"}),
                content_type="application/json")
        tail = max(1, min(tail, self.MACHINE_LOG_CAP))
        lines = await self.store.lrange(Keys.machine_logs(machine_id),
                                        -tail, -1)
        return web.json_response({"lines": lines})

    def _require_operator(self, request: web.Request):
        """Quota writes are operator actions (the reference gates them on
        cluster-admin tokens); tpu9's operator is the default workspace —
        with a USER token. Runner/worker tokens of the default workspace
        ride inside user-controlled containers (builds run arbitrary user
        commands with one); token-type-blind operator checks would be a
        straight privilege escalation to minting durable keys."""
        ws = self._ws(request)
        if (ws.workspace_id != self.default_workspace.workspace_id
                or request.get("token_type") != "workspace"):
            raise web.HTTPForbidden(
                text=json.dumps({"error": "operator token required"}),
                content_type="application/json")
        return ws

    async def _get_concurrency_limit(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        limit = await self.backend.get_concurrency_limit(ws.workspace_id)
        cpu, chips = await self.quota.in_use(ws.workspace_id)
        return web.json_response({
            "limit": limit, "in_use": {"cpu_millicores": cpu,
                                       "tpu_chips": chips}})

    async def _set_concurrency_limit(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        data = await request.json()
        await self.backend.set_concurrency_limit(
            request.match_info["workspace_id"],
            tpu_chip_limit=int(data.get("tpu_chip_limit", 0)),
            cpu_millicore_limit=int(data.get("cpu_millicore_limit", 0)))
        return web.json_response({"ok": True})

    async def _delete_concurrency_limit(self, request: web.Request) -> web.Response:
        self._require_operator(request)
        ok = await self.backend.delete_concurrency_limit(
            request.match_info["workspace_id"])
        return web.json_response({"ok": ok})

    async def _deployments_by_app(self, workspace_id: str) -> dict[str, list]:
        """app_id → deployments, one stub fetch per deployment."""
        grouped: dict[str, list] = {}
        for dep in await self.backend.list_deployments(workspace_id):
            stub = await self.backend.get_stub(dep.stub_id)
            if stub is not None:
                grouped.setdefault(stub.app_id, []).append(dep)
        return grouped

    async def _list_apps(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        grouped = await self._deployments_by_app(ws.workspace_id)
        return web.json_response([
            {**app, "deployments": [d.to_dict() for d in
                                    grouped.get(app["app_id"], [])]}
            for app in await self.backend.list_apps(ws.workspace_id)])

    async def _delete_app(self, request: web.Request) -> web.Response:
        """Delete an app: deactivate + drain every deployment under it
        (reference app group's delete semantics)."""
        ws = self._ws(request)
        apps = await self.backend.list_apps(ws.workspace_id)
        app = next((a for a in apps
                    if a["app_id"] == request.match_info["app_id"]), None)
        if app is None:
            return web.json_response({"error": "not found"}, status=404)
        grouped = await self._deployments_by_app(ws.workspace_id)
        drained = 0
        for dep in grouped.get(app["app_id"], []):
            await self.backend.set_deployment_active(dep.deployment_id,
                                                     False)
            await self.endpoints.drain_stub(dep.stub_id)
            drained += 1
        await self.backend.delete_app(app["app_id"])
        return web.json_response({"ok": True, "deployments_drained": drained})

    async def _list_containers(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        out = []
        for stub in await self.backend.list_stubs(ws.workspace_id):
            for st in await self.containers.containers_by_stub(stub.stub_id):
                out.append(st.to_dict())
        return web.json_response(out)

    async def _container_for(self, request: web.Request, key: str = "id",
                             allow_worker: bool = True):
        """Workspace-scoped container lookup — 404 on missing or foreign
        containers. ``allow_worker`` lets worker tokens act cross-workspace
        like the reference's repo-over-gRPC services."""
        ws = self._ws(request)
        container_id = await self.containers.resolve(
            request.match_info[key])
        state = await self.containers.get_state(container_id)
        worker_ok = allow_worker and request.get("is_worker")
        if state is None or (not worker_ok
                             and state.workspace_id != ws.workspace_id):
            raise web.HTTPNotFound(
                text=json.dumps({"error": "container not found"}),
                content_type="application/json")
        return state

    async def _stop_container(self, request: web.Request) -> web.Response:
        state = await self._container_for(request)
        ok = await self.scheduler.stop_container(state.container_id)
        return web.json_response({"ok": ok})

    async def _container_logs(self, request: web.Request) -> web.Response:
        # post-mortem reads must outlive the 60 s state TTL: fall back to the
        # durable ownership key when state is gone but logs remain
        ws = self._ws(request)
        container_id = await self.containers.resolve(request.match_info["id"])
        state = await self.containers.get_state(container_id)
        owner = (state.workspace_id if state is not None
                 else await self.containers.get_owner(container_id))
        if owner is None or (not request.get("is_worker")
                             and owner != ws.workspace_id):
            raise web.HTTPNotFound(
                text=json.dumps({"error": "container not found"}),
                content_type="application/json")
        since = request.query.get("since", "0")
        entries = await self.containers.read_logs(container_id,
                                                  last_id=since)
        return web.json_response(
            [{"id": eid, **e} for eid, e in entries])

    async def _container_shell(self, request: web.Request) -> web.StreamResponse:
        """Interactive shell: websocket ⇄ worker PTY over the state bus
        (reference: shell abstraction's gateway TCP tunnel, shell/http.go).
        Client sends JSON {d: b64} input / {resize: [rows, cols]}; receives
        JSON {d: b64} output and a final {exit: code}."""
        state = await self._container_for(request)
        if not state.worker_id:
            return web.json_response({"error": "container has no worker"},
                                     status=409)
        session_id = f"shell-{hashlib.sha1(os.urandom(16)).hexdigest()[:12]}"
        ws = web.WebSocketResponse()
        await ws.prepare(request)

        # first-frame protocol: a client may open with {"cmd": [...]} to run
        # a one-shot command under the PTY instead of an interactive shell
        # (scripted `tpu9 shell` with piped stdin). Interactive clients send
        # a resize first, which simply forwards as normal input below.
        cmd = None
        first_payload = None
        try:
            first = await ws.receive(timeout=2.0)
            if first.type == web.WSMsgType.TEXT:
                first_payload = json.loads(first.data)
                if isinstance(first_payload.get("cmd"), list):
                    cmd = first_payload["cmd"]
                    first_payload = None
        except (asyncio.TimeoutError, json.JSONDecodeError):
            pass

        publish_payload = {
            "container_id": state.container_id, "session": session_id,
        }
        if cmd:
            publish_payload["cmd"] = cmd
        subscribers = await self.store.publish(
            f"container:shell:{state.worker_id}", publish_payload)
        if not subscribers:
            # pubsub is fire-and-forget: zero subscribers means the worker
            # is down/restarting — error now instead of hanging the client
            await ws.send_json({"error": "worker unavailable", "exit": -1})
            await ws.close()
            return ws
        out_key = f"shell:out:{session_id}"

        async def pump_down() -> None:
            last_id = "0"
            while not ws.closed:
                entries = await self.containers.store.xread(
                    out_key, last_id=last_id, timeout=1.0)
                for eid, entry in entries:
                    last_id = eid
                    await ws.send_json(entry)
                    if "exit" in entry:
                        await ws.close()
                        return

        down = asyncio.create_task(pump_down())
        try:
            if first_payload is not None:
                await self.store.xadd(f"shell:in:{session_id}",
                                      first_payload)
            async for msg in ws:
                if msg.type != web.WSMsgType.TEXT:
                    continue
                try:
                    payload = json.loads(msg.data)
                except json.JSONDecodeError:
                    continue
                await self.store.xadd(f"shell:in:{session_id}", payload)
        finally:
            await self.store.xadd(f"shell:in:{session_id}", {"close": True})
            down.cancel()
        return ws

    async def _list_disks(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(await self.disks.list(ws.workspace_id))

    async def _disk_snapshot(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        out = await self.disks.snapshot(ws.workspace_id,
                                        request.match_info["name"])
        status = 200 if "error" not in out else 409
        return web.json_response(out, status=status)

    async def _disk_delete(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        ok = await self.disks.delete(ws.workspace_id,
                                     request.match_info["name"])
        return web.json_response({"ok": ok})

    async def _internal_disk_manifest_put(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        blob = await request.text()
        from ..images import ImageManifest
        try:
            manifest = ImageManifest.from_json(blob)
        except Exception as exc:   # noqa: BLE001
            return web.json_response({"error": f"bad manifest: {exc}"},
                                     status=400)
        await self.backend.set_disk_snapshot(
            request.match_info["workspace_id"], request.match_info["name"],
            request.match_info["snapshot_id"], blob, manifest.total_bytes)
        return web.json_response({"ok": True})

    async def _internal_disk_manifest_get(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        blob = await self.backend.get_disk_snapshot_manifest(
            request.match_info["snapshot_id"])
        if blob is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(text=blob, content_type="application/json")

    async def _internal_sbxsnap_put(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        blob = await request.text()
        from ..images import ImageManifest
        try:
            manifest = ImageManifest.from_json(blob)
        except Exception as exc:   # noqa: BLE001
            return web.json_response({"error": f"bad manifest: {exc}"},
                                     status=400)
        kind = request.query.get("kind", "workdir")
        if kind not in ("workdir", "criu"):
            return web.json_response({"error": f"bad kind {kind!r}"},
                                     status=400)
        await self.backend.put_sandbox_snapshot(
            request.match_info["snapshot_id"],
            request.match_info["workspace_id"],
            request.match_info["container_id"], blob, manifest.total_bytes,
            kind=kind)
        return web.json_response({"ok": True})

    def _ckpt_manifest_path(self, checkpoint_id: str) -> str:
        # checkpoint manifests are ImageManifests, stored the way the image
        # registry stores its own (JSON files under registry_dir) — NOT as
        # backend rows like sandbox snapshots: the registry dir is already
        # the durability domain for every manifest the scheduler hands out
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", checkpoint_id):
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "bad checkpoint id"}),
                content_type="application/json")
        d = os.path.join(self.cfg.image.registry_dir, "checkpoints")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{checkpoint_id}.json")

    async def _internal_ckpt_record(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        checkpoint_id = await self.backend.create_checkpoint(
            request.match_info["stub_id"],
            request.match_info["workspace_id"],
            request.match_info["container_id"])
        return web.json_response({"checkpoint_id": checkpoint_id})

    async def _internal_ckpt_status(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        body = await request.json()
        await self.backend.update_checkpoint(
            request.match_info["checkpoint_id"],
            str(body.get("status", "failed")),
            str(body.get("remote_key", "")), int(body.get("size", 0)))
        return web.json_response({"ok": True})

    async def _internal_ckpt_manifest_put(self,
                                          request: web.Request) -> web.Response:
        self._require_worker(request)
        blob = await request.text()
        from ..images import ImageManifest
        try:
            ImageManifest.from_json(blob)
        except Exception as exc:   # noqa: BLE001
            return web.json_response({"error": f"bad manifest: {exc}"},
                                     status=400)
        path = self._ckpt_manifest_path(request.match_info["checkpoint_id"])

        def _write() -> None:      # multi-MB manifests must not stall the
            tmp = f"{path}.tmp"    # event loop (every request shares it)
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)  # readers never see a partial manifest

        await asyncio.to_thread(_write)
        return web.json_response({"ok": True})

    async def _internal_ckpt_manifest_get(self,
                                          request: web.Request) -> web.Response:
        self._require_worker(request)
        path = self._ckpt_manifest_path(request.match_info["checkpoint_id"])
        if not os.path.exists(path):
            return web.json_response({"error": "not found"}, status=404)
        blob = await asyncio.to_thread(lambda: open(path).read())
        return web.Response(text=blob, content_type="application/json")

    async def _internal_sbxsnap_get(self, request: web.Request) -> web.Response:
        self._require_worker(request)
        snap = await self.backend.get_sandbox_snapshot(
            request.match_info["snapshot_id"])
        if snap is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(text=snap["manifest"],
                            content_type="application/json")

    async def _list_tasks(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(await self.backend.list_tasks(ws.workspace_id))

    async def _list_workers(self, request: web.Request) -> web.Response:
        self._require_operator(request)   # fleet topology: operator-only
        workers = await self.workers.list()
        out = []
        for w in workers:
            d = w.to_dict()
            d["alive"] = await self.workers.is_alive(w.worker_id)
            out.append(d)
        return web.json_response(out)

    async def _list_stubs(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(
            [s.to_dict() for s in await self.backend.list_stubs(ws.workspace_id)])

    async def _list_secrets(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        return web.json_response(await self.backend.list_secrets(ws.workspace_id))

    async def _upsert_secret(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        data = await request.json()
        await self.backend.upsert_secret(ws.workspace_id, data["name"],
                                         data["value"])
        return web.json_response({"ok": True})

    async def _delete_secret(self, request: web.Request) -> web.Response:
        ws = self._ws(request)
        ok = await self.backend.delete_secret(ws.workspace_id,
                                              request.match_info["name"])
        return web.json_response({"ok": ok})
