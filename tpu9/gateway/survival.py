"""Request survivability (ISSUE 15): deadline propagation, automatic
failover, and mid-stream resumption state for the gateway invoke paths.

The serverless premise only holds if the *request* survives the replica:
PR 14's health plane detects a dead/stalled replica and routes new work
around it, but everything in flight there still died with it. This
module is the recovery half —

- **Deadlines**: a client budget (``X-Tpu9-Budget-S``, relative seconds)
  becomes one monotonic deadline at ingest; every retry attempt forwards
  the *remaining* budget, so spent time is deducted, never reset.
- **Transparent retry** (buffered path): :func:`submit_with_failover`
  re-submits a failed dispatch through the router with jittered
  exponential backoff, a total-attempts budget, and the failed replica
  excluded from placement.
- **Mid-stream resumption** (SSE path): :class:`StreamResumption` holds
  the token watermark — tokens already delivered to the client — and
  builds the replay request (``prompt + delivered`` as the new prefill,
  budget reduced by the watermark). The prefix cache makes the replay
  cheap on any replica that has seen the prefix; the watermark guarantees
  the client never sees a duplicated or skipped token across the splice.
- **Idempotency journal**: a store-backed per-request entry (request id,
  watermark, attempt count) so a *client-initiated* retry of an
  in-flight or completed request attaches to the journal instead of
  double-executing — the race the router's queue-wait deadline comment
  has called out since PR 2.

Everything here is pure bookkeeping over plain types (the unit-testable
core); the gateway's ``_serve_stub``/``_serve_stub_stream`` own the
actual HTTP/relay plumbing.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..observability.decisions import ledger, rej
from ..observability.trace import tracer
from ..utils.backoff import BackoffPolicy

BUDGET_HEADER = "X-Tpu9-Budget-S"
REQUEST_ID_HEADER = "X-Tpu9-Request-Id"
REPLAY_HEADER = "X-Tpu9-Replayed"
# client opt-out of gateway-initiated retries: non-idempotent handlers
# (a POST with side effects outside the serverless idempotent-handler
# contract) set this to guarantee at-most-once dispatch
NO_RETRY_HEADER = "X-Tpu9-No-Retry"

# engine-side deadline error prefix (serving.engine raises it; the runner
# maps it to 504; classify() treats it as final — the budget is SPENT,
# retrying would only burn chips on an answer the client stopped waiting
# for)
DEADLINE_ERROR = "deadline_exceeded"

OK, RETRYABLE, FATAL = "ok", "retryable", "fatal"


def parse_budget_s(raw: str) -> float:
    """Header value → relative budget seconds (0.0 = absent/invalid —
    an unparseable budget must not take the request down with a 400:
    the header is an optimization, not part of the request body)."""
    try:
        v = float(raw)
    except (TypeError, ValueError):
        return 0.0
    if v != v:                     # NaN: garbage, not a zero budget
        return 0.0
    return v if v > 0 else -1.0 if raw else 0.0


@dataclass
class RequestContext:
    """Per-request survivability state threaded through every attempt."""
    request_id: str = ""
    deadline_mono: float = 0.0     # 0 = no deadline
    # set once the journal entry reached a TERMINAL write: the gateway's
    # escape-hatch cleanup (exception/cancellation between begin and
    # finish) must clear only still-INFLIGHT entries — deleting a DONE
    # entry because the CLIENT disconnected after completion would let
    # its retry double-execute
    journal_closed: bool = False

    @classmethod
    def from_headers(cls, headers, request_id: str = "") -> "RequestContext":
        budget = parse_budget_s(headers.get(BUDGET_HEADER, ""))
        deadline = 0.0
        if budget > 0:
            deadline = time.monotonic() + budget
        elif budget < 0:
            deadline = time.monotonic()    # explicit non-positive budget:
            #                                already expired at the door
        return cls(request_id=request_id
                   or headers.get(REQUEST_ID_HEADER, ""),
                   deadline_mono=deadline)

    def remaining_s(self) -> Optional[float]:
        if self.deadline_mono <= 0:
            return None
        return self.deadline_mono - time.monotonic()

    def expired(self) -> bool:
        r = self.remaining_s()
        return r is not None and r <= 0


def classify_result(status: int, body: bytes = b"") -> str:
    """Is this ForwardResult worth a failover attempt?

    - ``502`` — transport-class failure (replica crash mid-request, RPC
      reset, drain-timeout kill): retry.
    - ``503`` with a runner "not ready" body — the container exists but
      its engine is dead/booting: retry (placement will avoid it).
    - ``500`` naming an engine failure — the serve loop died under this
      request: retry on another replica.
    - Everything else is final: router sheds (429/503 + Retry-After) are
      the CLIENT's retry contract, 4xx are the request's own fault, 504
      means a budget was already spent, and 200s are 200s.
    """
    if status < 400:
        return OK
    if status == 502:
        return RETRYABLE
    if status == 503 and b"not ready" in body:
        return RETRYABLE
    if status == 500 and (b"engine is dead" in body
                          or b"engine failure" in body
                          or b"engine stopped" in body):
        # "engine stopped" is the drain-timeout kill: the replica was
        # scaled down with this request still on it
        return RETRYABLE
    return FATAL


class FailoverBudget:
    """Attempt + backoff accounting for one request. ``attempt`` is the
    1-based number of the attempt currently in flight."""

    def __init__(self, max_attempts: int, backoff: BackoffPolicy,
                 deadline_mono: float = 0.0, rng=None):
        self.max_attempts = max(int(max_attempts), 1)
        self.backoff = backoff
        self.deadline_mono = deadline_mono
        self.rng = rng
        self.attempt = 1
        self.first_failure_mono = 0.0

    def note_failure(self) -> None:
        if self.first_failure_mono == 0.0:
            self.first_failure_mono = time.monotonic()

    def next_delay(self) -> Optional[float]:
        """Consume one retry: the backoff delay before the next attempt,
        or None when the attempts budget (or the deadline) is exhausted.
        The delay is clamped so a retry never sleeps past the deadline."""
        if self.attempt >= self.max_attempts:
            return None
        d = self.backoff.delay(self.attempt - 1, self.rng)
        if self.deadline_mono > 0:
            remaining = self.deadline_mono - time.monotonic()
            if remaining <= 0:
                return None
            d = min(d, max(remaining - 0.001, 0.0))
        self.attempt += 1
        return d


async def submit_with_failover(
        attempt_fn: Callable[[int, set], Awaitable[Any]],
        budget: FailoverBudget,
        classify: Callable[[int, bytes], str] = classify_result,
        on_failover: Optional[Callable[[int, Any, float], None]] = None,
        sleep: Callable[[float], Awaitable[None]] = asyncio.sleep):
    """Drive ``attempt_fn(attempt, avoid)`` until it returns a
    non-retryable ForwardResult or the budget runs out. ``avoid``
    accumulates replicas observed failing (the buffer deprioritizes
    them); ``on_failover(next_attempt, failed_result, delay)`` fires
    once per retry for spans/counters. Returns the final result — on
    exhaustion, the LAST failure (honest, not a synthesized 200)."""
    avoid: set[str] = set()
    # decision ledger (ISSUE 19): the classify verdict + attempt budget
    # behind every retry / give-up, keyed by the surrounding invoke
    # span's trace id (the fleet request id)
    req_id = tracer.current_trace_id()
    while True:
        result = await attempt_fn(budget.attempt, avoid)
        verdict = classify(result.status, result.body)
        if verdict != RETRYABLE:
            if verdict == FATAL:
                ledger.record(
                    "failover", "final", request_id=req_id,
                    chosen="return_error",
                    rejected=[rej("retry", f"verdict:{verdict}")],
                    signals={"status": result.status,
                             "attempt": budget.attempt,
                             "max_attempts": budget.max_attempts})
            return result
        budget.note_failure()
        delay = budget.next_delay()
        if delay is None:
            ledger.record(
                "failover", "give_up", request_id=req_id,
                chosen="return_last_failure",
                rejected=[rej("retry",
                              "attempts_exhausted"
                              if budget.attempt >= budget.max_attempts
                              else "deadline_exhausted")],
                signals={"status": result.status, "verdict": verdict,
                         "attempt": budget.attempt,
                         "max_attempts": budget.max_attempts})
            return result
        if getattr(result, "container_id", ""):
            avoid.add(result.container_id)
        # next_delay() consumed the retry: budget.attempt is now the
        # attempt about to run, budget.attempt - 1 the one that failed
        ledger.record(
            "failover", "retry", request_id=req_id,
            chosen=f"attempt_{budget.attempt}",
            rejected=[rej(getattr(result, "container_id", "") or "replica",
                          f"http_{result.status}")],
            signals={"verdict": verdict, "failed_status": result.status,
                     "failed_attempt": budget.attempt - 1,
                     "max_attempts": budget.max_attempts,
                     "backoff_s": round(delay, 4)})
        if on_failover is not None:
            on_failover(budget.attempt, result, delay)
        await sleep(delay)


# -- SSE / stream resumption --------------------------------------------------

class SseParser:
    """Incremental server-sent-event parser for the runner's token
    stream: feed raw relay chunks, get parsed ``data:`` JSON events.
    Non-JSON frames are surfaced as ``{"_raw": <bytes>}`` so the relay
    can still forward what it does not understand."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> list[dict]:
        self._buf += chunk
        events: list[dict] = []
        while b"\n\n" in self._buf:
            frame, self._buf = self._buf.split(b"\n\n", 1)
            frame = frame.strip()
            if not frame:
                continue
            if frame.startswith(b"data: "):
                try:
                    events.append(json.loads(frame[6:]))
                    continue
                except ValueError:
                    pass
            events.append({"_raw": frame})
        return events


def parse_llm_stream_body(body: bytes) -> Optional[dict]:
    """``{"prompt": [...ints], "max_new": N, "payload": {...}}`` when the
    request is a resumable LLM token-stream body, else None (non-LLM
    streams fall back to single-attempt relay — there is no watermark to
    splice on)."""
    try:
        payload = json.loads(body)
        tokens = payload.get("tokens") or payload.get("prompt_tokens")
        if not isinstance(tokens, list) or not tokens:
            return None
        prompt = [int(t) for t in tokens]
        max_new = int(payload.get("max_new_tokens", 32))
    except (ValueError, TypeError, AttributeError):
        return None
    if max_new <= 0:
        return None
    return {"prompt": prompt, "max_new": max_new, "payload": payload}


class StreamResumption:
    """Token-watermark bookkeeping for one SSE generation.

    The watermark is the number of generated tokens the CLIENT has been
    sent. A resume attempt replays ``prompt + delivered`` as a fresh
    prefill (cheap on any replica holding the prefix in its prefix
    cache) with the generation budget reduced by the watermark — so the
    spliced stream continues exactly one token after the last one the
    client saw: no duplicates, no gaps, regardless of how far ahead of
    the relay the dead replica had decoded."""

    def __init__(self, prompt: list[int], max_new: int,
                 payload: Optional[dict] = None):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.payload = dict(payload or {})
        self.delivered: list[int] = []
        self.finished = False        # saw a done event
        # kvwire block-ship resume (ISSUE 16): the latest kv_key event
        # the exporting replica announced, and how many tokens of the
        # sequence that payload covers. A resume attempt forwards it as
        # an adopt_kv hint — the target splices the shipped blocks and
        # the replayed prefill collapses to the unshipped suffix. Always
        # the LATEST key: drain re-exports supersede the prefill ship.
        self.kv_key = ""
        self.kv_tokens = 0

    @property
    def watermark(self) -> int:
        return len(self.delivered)

    @property
    def remaining(self) -> int:
        return max(self.max_new - self.watermark, 0)

    def note_token(self, tok: int) -> None:
        self.delivered.append(int(tok))

    def note_kv(self, key: str, n_tokens: int) -> None:
        """A ``kv_key`` announcement from the serving replica (emitted
        after prefill, or by a drain re-export). Swallowed by the relay
        — clients never see transport bookkeeping."""
        if key:
            self.kv_key = str(key)
            self.kv_tokens = int(n_tokens or 0)

    @property
    def ended_on_eos(self) -> bool:
        """True when the last delivered token is the request's declared
        EOS — the generation FINISHED even though the replica died
        before its done event. Only knowable when the client declared
        ``eos_id`` in the request payload; an engine-config EOS the
        gateway cannot see is a documented resume limitation (a resumed
        attempt would sample past it)."""
        try:
            eos = int(self.payload.get("eos_id", -1))
        except (TypeError, ValueError):
            return False
        return eos >= 0 and bool(self.delivered) \
            and self.delivered[-1] == eos

    def resume_payload(self) -> bytes:
        """Request body for the next attempt: delivered tokens join the
        prompt, budget is what is still owed."""
        out = dict(self.payload)
        out.pop("prompt_tokens", None)
        out["tokens"] = self.prompt + self.delivered
        out["max_new_tokens"] = self.remaining
        out["stream"] = True
        out.pop("kv_export", None)      # the handoff already happened
        if self.kv_key:
            # block-ship resume hint: strictly best-effort on the target
            # (fetch miss / geometry mismatch / pool pressure all fall
            # back to the re-prefill this body already encodes)
            out["adopt_kv"] = {"key": self.kv_key,
                               "n_tokens": self.kv_tokens}
        return json.dumps(out).encode()

    def done_event(self) -> dict:
        """The client-facing terminal event: the FULL generated sequence
        (a resumed attempt's own done event only knows its fresh suffix)."""
        self.finished = True
        return {"done": True, "tokens": list(self.delivered)}


# -- idempotency journal ------------------------------------------------------

NEW, INFLIGHT, DONE = "new", "inflight", "done"


class RequestJournal:
    """Store-backed per-request journal keyed by the client's
    ``X-Tpu9-Request-Id``. ``begin`` is a compare-and-set so two
    concurrent submits of the same id resolve to exactly one executor;
    the loser (and any later client retry) sees the journal state
    instead of re-executing. Completed entries retain small response
    bodies for true replay; larger ones dedupe with a summary."""

    def __init__(self, store, ttl_s: float = 600.0,
                 body_cap: int = 65536):
        self.store = store
        self.ttl_s = ttl_s
        self.body_cap = body_cap

    @staticmethod
    def _key(workspace_id: str, request_id: str,
             stub_id: str = "") -> str:
        # scoped per DEPLOYMENT too: the same client id against two
        # different stubs is two different requests — without the stub
        # in the key, stub B's request would replay stub A's response
        return f"reqjournal:{workspace_id}:{stub_id}:{request_id}"

    async def begin(self, workspace_id: str, request_id: str,
                    stub_id: str = "") -> tuple[str, dict]:
        """(state, record): ``new`` = this caller owns execution;
        ``inflight`` = another attempt is executing; ``done`` = the
        request already completed (record carries the replay)."""
        key = self._key(workspace_id, request_id, stub_id)
        rec = {"state": INFLIGHT, "watermark": 0, "attempts": 1,
               "ts": time.time()}
        if await self.store.cas(key, None, rec, ttl=self.ttl_s):
            return NEW, rec
        cur = await self.store.get(key)
        if cur is None:
            # expired between cas and get: take ownership via a SECOND
            # cas — an unconditional set here would let two racers both
            # win and double-execute, the exact race the journal exists
            # to close
            if await self.store.cas(key, None, rec, ttl=self.ttl_s):
                return NEW, rec
            cur = await self.store.get(key)
            if cur is None:
                # pathological churn (entry expiring faster than we can
                # read it): refuse ownership — a spurious 409 beats a
                # double execution
                return INFLIGHT, rec
        if cur.get("state") == DONE:
            return DONE, cur
        return INFLIGHT, cur

    async def update(self, workspace_id: str, request_id: str,
                     watermark: int, attempts: int,
                     stub_id: str = "") -> None:
        """Record a failover: watermark + attempt count (the evidence a
        post-incident 'did my stream duplicate tokens' query needs)."""
        key = self._key(workspace_id, request_id, stub_id)
        await self.store.set(key, {"state": INFLIGHT,
                                   "watermark": int(watermark),
                                   "attempts": int(attempts),
                                   "ts": time.time()}, ttl=self.ttl_s)

    async def finish(self, workspace_id: str, request_id: str,
                     status: int, body: bytes = b"",
                     watermark: int = 0, attempts: int = 1,
                     stub_id: str = "", content_type: str = "") -> None:
        """Close the entry. Only outcomes worth REPLAYING are kept as
        DONE: successes and deterministic client errors. Sheds (429),
        gateway 5xx and spent-budget 504s CLEAR the entry instead — the
        client was explicitly told to retry (Retry-After) or will retry
        with a fresh budget, and pinning the stale failure under its
        request id for the whole TTL would make that retry replay the
        failure instead of executing."""
        key = self._key(workspace_id, request_id, stub_id)
        if status >= 500 or status in (429, 499):
            await self.store.delete(key)
            return
        rec: dict = {"state": DONE, "status": int(status),
                     "watermark": int(watermark),
                     "attempts": int(attempts), "ts": time.time()}
        if body and len(body) <= self.body_cap:
            rec["body_b64"] = base64.b64encode(body).decode()
            if content_type:
                # the replay must not re-label a text/csv body as JSON
                rec["ctype"] = content_type
        await self.store.set(key, rec, ttl=self.ttl_s)

    @staticmethod
    def replay_body(rec: dict) -> Optional[bytes]:
        raw = rec.get("body_b64")
        if not raw:
            return None
        try:
            return base64.b64decode(raw)
        except (ValueError, TypeError):
            return None


@dataclass
class AttemptOutcome:
    """What one stream attempt ended as — the relay loop's verdict."""
    kind: str                      # "done" | "failed" | "client_gone"
    reason: str = ""
    replica: str = ""
    error_body: bytes = b""
    extras: dict = field(default_factory=dict)
