from .gateway import Gateway

__all__ = ["Gateway"]
