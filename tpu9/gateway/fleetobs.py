"""FleetObserver: the gateway's fleet-evidence sampler (ISSUE 12).

Owns the bounded :class:`~tpu9.observability.timeline.TimelineStore`, the
:class:`~tpu9.observability.slo.SloEvaluator` and the
:class:`~tpu9.observability.slo.GoodputAccountant`, and wires them to the
cadences the system already has:

- **pressure-heartbeat cadence** (``/rpc/llm/pressure`` ingest): every
  accepted engine heartbeat records that replica's timeline series
  (tokens/sec, KV blocks, spec acceptance, recompile sentinel, MFU/MBU
  priced from the shipped physics constants) and feeds the goodput
  accountant's engine counters;
- **sampler tick** (``slo.sample_interval_s``): per-stub router series
  (queue depth, shed/submitted counters, TTFT/queue-wait percentiles,
  pressure), SLO burn-rate evaluation folded into the autoscaler
  pressure feed via ``RouterSignals.slo_sample``, goodput router
  counters, Prometheus gauge publication, and timeline pruning.

The observer also owns stale-replica aging for the ``/api/v1/metrics``
``engines`` merge: a replica silent longer than ``slo.stale_after_s``
(default 3 runner heartbeats) is dropped (and its accountant delta base forgotten) instead
of serving dead stats until the store TTL.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ..observability.slo import GoodputAccountant, SloEvaluator
from ..observability.timeline import TimelineStore
from ..observability.usage import bucket_of, usage_key
from ..utils.aio import event_wait, reap

log = logging.getLogger("tpu9.gateway")

# engine heartbeat fields mirrored 1:1 into per-replica timeline series
ENGINE_SERIES = ("tokens_per_sec", "token_pressure", "queued",
                 "kv_blocks_free", "kv_blocks_used", "kv_blocks_reserved",
                 "spec_acceptance_rate", "graph_compiles_post_warmup",
                 "active_streams",
                 # replica health plane (ISSUE 14): HBM watermarks (live
                 # vs planner-predicted — the drift graph) + the liveness
                 # watermark ages behind the watchdog's verdict
                 "hbm_used_gb_per_chip", "hbm_peak_gb_per_chip",
                 "hbm_predicted_gb_per_chip", "hbm_limit_gb_per_chip",
                 "windows_processed", "last_dispatch_age_s",
                 "last_progress_age_s",
                 # replica-level prefix-cache effectiveness (ISSUE 2
                 # satellite shipped it; ISSUE 18's wirecheck caught that
                 # no gateway consumer ever read it): the per-replica
                 # twin of the router-side tpu9_router_prefix_hit_rate —
                 # divergence between the two is the affinity router
                 # mis-steering
                 "prefix_hits", "prefix_misses", "prefix_hit_rate",
                 # kvwire block-ship plane (ISSUE 16): export/import
                 # ledger + ship latency — `tpu9 top`'s migration view
                 "kvwire_blocks_exported", "kvwire_blocks_imported",
                 "kvwire_bytes_exported", "kvwire_bytes_imported",
                 "kvwire_import_hits", "kvwire_import_fallbacks",
                 "kvwire_ship_p50_s", "kvwire_ship_p95_s",
                 # scale-out plane (ISSUE 17): execute-while-scaling
                 # per-group weight readiness — the router's admission
                 # fence and `tpu9 scaleout`'s readiness fraction
                 "scaleout_groups_total", "scaleout_groups_ready",
                 "scaleout_ready_frac",
                 # KV tiering plane (ISSUE 20): tier occupancy + paging
                 # traffic — `tpu9 top`'s KV-tier columns and the
                 # hit-rate-by-tier split
                 "kvtier_device_blocks", "kvtier_device_bytes",
                 "kvtier_host_blocks", "kvtier_host_bytes",
                 "kvtier_host_entries", "kvtier_host_evictions",
                 "kvtier_downpages", "kvtier_uppages",
                 "kvtier_uppage_failures", "kvtier_peer_spills",
                 "kvtier_hits_device", "kvtier_hits_host",
                 "kvtier_downpage_p50_s", "kvtier_downpage_p95_s",
                 "kvtier_uppage_p50_s", "kvtier_uppage_p95_s")
# router snapshot fields mirrored into per-stub timeline series
ROUTER_SERIES = ("queue_depth", "shed_rate", "pressure")
# worker-heartbeated cache-plane counters mirrored 1:1 into per-worker
# cache.* timeline series (ISSUE 13)
CACHE_SERIES = ("local_hits", "peer_hits", "source_fetches", "peer_errors",
                "hedged_reads", "hedge_wins", "hedge_wasted_bytes",
                "bytes_local", "bytes_peer", "bytes_source")
WEIGHTPOOL_SERIES = ("hits", "misses", "evictions", "rejected", "inserts",
                     "entries", "bytes")


def _num(d: dict, key: str, default: float = 0.0) -> float:
    try:
        return float(d.get(key, default))
    except (TypeError, ValueError):
        return default


class FleetObserver:
    def __init__(self, cfg, store, fleet_router=None, scaleout=None):
        """``cfg`` is an AppConfig.slo (SloConfig). ``scaleout`` is an
        optional :class:`~tpu9.scaleout.coordinator.ScaleoutCoordinator`
        (ISSUE 17): when present, worker cache-plane snapshots and engine
        heartbeats feed its group ledger, and every sampler tick
        republishes the refreshed multicast tree plan to the store."""
        self.cfg = cfg
        self.store = store
        self.fleet_router = fleet_router
        self.scaleout = scaleout
        self.timeline = TimelineStore(
            capacity=cfg.timeline_capacity,
            max_series=cfg.timeline_max_series,
            idle_ttl_s=cfg.timeline_idle_ttl_s)
        self.evaluator = SloEvaluator(self.timeline, cfg.objectives,
                                      burn_alert=cfg.burn_alert)
        self.goodput = GoodputAccountant(window_s=cfg.goodput_window_s)
        self._task: Optional[asyncio.Task] = None
        self._stopping = asyncio.Event()

    @property
    def stale_after_s(self) -> float:
        return self.cfg.stale_after_s

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "FleetObserver":
        if self._task is None:
            self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stopping.set()
        if self._task is not None:
            await reap(self._task)
            self._task = None

    async def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                await self.sample()
            except Exception:   # noqa: BLE001 — evidence collection must
                log.exception("fleet observer tick failed")  # not die
            await event_wait(self._stopping, self.cfg.sample_interval_s)

    # -- heartbeat-cadence ingest (called from /rpc/llm/pressure) ------------

    def ingest_heartbeat(self, container_id: str, workspace_id: str,
                         stub_id: str, token_pressure: float,
                         active_streams: int,
                         extra: Optional[dict] = None) -> None:
        """One accepted engine heartbeat → per-replica timeline series +
        goodput engine counters. Values arrive as the flat scalars the
        runner ships (strings after a store round-trip are fine)."""
        stats = dict(extra or {})
        stats["token_pressure"] = token_pressure
        stats["active_streams"] = active_streams
        prefix = f"engine.{container_id}."
        for key in ENGINE_SERIES:
            if key in stats:
                self.timeline.record(prefix + key, _num(stats, key))
        # replica health (ISSUE 14): numeric state series (0 ok /
        # 1 degraded / 2 stalled), tpu9_health_*/tpu9_hbm_* gauges, and
        # the routing fold — a `stalled` verdict ejects the replica from
        # affinity/JSQ the way draining does, a recovered one restores it
        if "health" in stats:
            from ..observability.health import health_code, publish_health
            state = str(stats.get("health", ""))
            self.timeline.record(prefix + "health", health_code(state))
            publish_health(container_id, stats)
            note = getattr(self.fleet_router, "note_replica_health", None)
            if note is not None:     # duck-typed router fakes in tests
                note(container_id, state,
                     reason=str(stats.get("health_reason", "")))
        # kvwire gauges (ISSUE 16): only for replicas that ship blocks —
        # a fleet with shipping off mints zero extra series
        if any(k.startswith("kvwire_") for k in stats):
            from ..observability.health import publish_kvwire
            publish_kvwire(container_id, stats)
        # KV tiering gauges (ISSUE 20): only replicas running a host tier
        # emit kvtier_* scalars, so an untiered fleet mints zero series
        if any(k.startswith("kvtier_") for k in stats):
            from ..observability.health import publish_kvtier
            publish_kvtier(container_id, stats)
            # the directory fold also rides the observer path so
            # heartbeats reach it even between dispatches (the dispatch
            # path re-folds the same snapshot — observe is idempotent)
            pdir = getattr(self.fleet_router, "prefix_dir", None)
            if pdir is not None:
                pdir.observe_replica(container_id, stats)
        # scale-out plane (ISSUE 17): per-group readiness → coordinator
        # ledger (serving-plane truth for the report + admission fence),
        # measured bring-up → router signals (the predictive controller's
        # scale-down guard must use MEASURED re-acquisition cost)
        if self.scaleout is not None and "scaleout_ready_frac" in stats:
            self.scaleout.observe_heartbeat(container_id, stats)
        ready_s = _num(stats, "coldstart_ready_s")
        if ready_s > 0 and self.fleet_router is not None:
            note = getattr(self.fleet_router.signals, "note_bringup", None)
            if note is not None:    # duck-typed router fakes in tests
                note(stub_id, ready_s)
        # MFU/MBU priced control-plane-side from the engine's physics
        # constants (bytes / FLOPs per token per chip) × tokens/sec,
        # against the chip's public peaks — honest ~0 on CPU hosts
        tps = _num(stats, "tokens_per_sec")
        bpt = _num(stats, "decode_bytes_per_token_per_chip")
        fpt = _num(stats, "decode_flops_per_token_per_chip")
        if tps > 0 and (bpt > 0 or fpt > 0):
            from ..benchsuite.physics import chip_spec
            spec = chip_spec(str(stats.get("device_kind", "")))
            self.timeline.record(prefix + "mbu",
                                 tps * bpt / (spec.hbm_gbps * 1e9))
            self.timeline.record(prefix + "mfu",
                                 tps * fpt / (spec.peak_bf16_tflops * 1e12))
        self.goodput.engine_sample(container_id, workspace_id, stub_id,
                                   stats)

    # -- sampler tick --------------------------------------------------------

    async def sample(self) -> None:
        """One observer tick: router series, SLO evaluation + pressure
        fold, goodput router counters, gauge publication, pruning."""
        if self.fleet_router is not None:
            signals = self.fleet_router.signals
            seen_stubs: set = set()
            for stub in self.fleet_router.active_stubs():
                sid = stub.stub_id
                seen_stubs.add(sid)
                snap = signals.snapshot(sid)
                prefix = f"router.{sid}."
                # LIVE fair-queue depth, not the last dispatch-time
                # sample: a burst that sheds between dispatch passes
                # must still show the queue it built
                if hasattr(self.fleet_router, "queue_depth"):
                    snap["queue_depth"] = self.fleet_router.queue_depth(sid)
                for key in ROUTER_SERIES:
                    self.timeline.record(prefix + key,
                                         float(snap.get(key, 0.0)))
                # cumulative counters the burn windows differentiate
                self.timeline.record(prefix + "submitted_total",
                                     float(snap.get("submitted", 0)))
                self.timeline.record(prefix + "shed_total",
                                     float(snap.get("shed", 0)))
                lat = snap.get("latency") or {}
                qw_total = 0.0
                for phase, row in lat.items():
                    self.timeline.record(f"{prefix}{phase}_p50_s",
                                         row.get("p50_s", 0.0))
                    self.timeline.record(f"{prefix}{phase}_p95_s",
                                         row.get("p95_s", 0.0))
                    if phase == "queue_wait":
                        # count × mean == cumulative queue-wait seconds
                        qw_total = (row.get("count", 0)
                                    * row.get("mean_s", 0.0))
                # SLO burn: evaluate, publish, fold into pressure
                evaluated = self.evaluator.evaluate(sid)
                for name, entry in evaluated.items():
                    self.timeline.record(
                        f"slo.{sid}.{name}.burn_fast",
                        entry["fast"]["burn"])
                    self.timeline.record(
                        f"slo.{sid}.{name}.burn_slow",
                        entry["slow"]["burn"])
                self.evaluator.publish(sid, evaluated)
                # worst slow-window burn rides along (ISSUE 17): the
                # predictive controller projects the FAST burn's slope
                # against the slow window's remaining budget
                signals.slo_sample(
                    sid, self.evaluator.max_fast_burn(evaluated),
                    max((e["slow"]["burn"] for e in evaluated.values()),
                        default=0.0))
                self.goodput.router_sample(
                    sid, stub.workspace_id,
                    submitted_total=float(snap.get("submitted", 0)),
                    shed_total=float(snap.get("shed", 0)),
                    queue_wait_total_s=qw_total)
            # stub churn (ISSUE 18): a stub that left active_stubs()
            # takes its per-stub gauges and rolling state with it — the
            # same prune filter_engines applies to replica series, at
            # the stub granularity
            for sid in getattr(self, "_sampled_stubs", set()) - seen_stubs:
                signals.forget_stub(sid)
                self.evaluator.forget_stub(sid)
                self.goodput.forget_stub(sid)
            self._sampled_stubs = seen_stubs
        await self.sample_cache_plane()
        self.sample_decisions()
        self.goodput.publish(await self.goodput_snapshot())
        self.timeline.prune()
        # decision-ledger index pruning rides the same tick (ISSUE 19):
        # finished requests' chains age out with timeline retention
        from ..observability.decisions import ledger as decision_ledger
        decision_ledger.prune()

    def sample_decisions(self) -> None:
        """Autoscaler verdicts → ``scaleout.{stub}.*`` timeline series
        (ISSUE 19 satellite): each predictive tick already left one
        ledger record; mirror its direction / projection / guard signals
        into the bounded rings so `tpu9 scaleout` and the dashboards get
        scaling history, not just the latest verdict. Seq-cursored so a
        record is sampled exactly once."""
        from ..observability.decisions import ledger as decision_ledger
        direction = {"up": 1.0, "down": -1.0, "hold": 0.0, "fallback": 0.0}
        recs, self._dec_cursor = decision_ledger.export_new(
            since_seq=getattr(self, "_dec_cursor", 0), limit=1000)
        for rec in recs:
            if rec.get("plane") != "autoscaler" \
                    or rec.get("decision") != "decide_scale":
                continue
            sid = rec.get("stub_id") or "fleet"
            sig = rec.get("signals") or {}
            prefix = f"scaleout.{sid}."
            self.timeline.record(prefix + "direction",
                                 direction.get(sig.get("action", ""), 0.0),
                                 ts=rec.get("ts"))
            for name in ("projected", "desired", "bringup_guard"):
                if name in sig:
                    self.timeline.record(prefix + name,
                                         _num(sig, name), ts=rec.get("ts"))

    async def sample_cache_plane(self) -> None:
        """Worker-heartbeated cache/weight-pool snapshots → per-worker
        (and per-peer) timeline series (ISSUE 13): the restore and
        weight-distribution plane's history — what the ROADMAP item-3
        scale-out bench reads to see N replicas share one peer tree."""
        import json
        for key in await self.store.keys("worker:cache:*"):
            raw = await self.store.get(key)
            if not raw:
                continue
            try:
                snap = json.loads(raw)
            except (ValueError, TypeError):
                continue
            wid = key.rsplit(":", 1)[-1]
            cache = snap.get("cache") or {}
            if self.scaleout is not None:
                # cache-plane truth for the multicast tree (ISSUE 17):
                # which replica HOLDS which shard groups, and the
                # per-peer latency EWMAs the edge picker weighs
                self.scaleout.observe_worker(wid, snap)
            prefix = f"cache.{wid}."
            for name in CACHE_SERIES:
                if name in cache:
                    self.timeline.record(prefix + name, _num(cache, name))
            for tier in ("local", "peer", "source"):
                rate = f"{tier}_bytes_per_s"
                if rate in snap:
                    self.timeline.record(prefix + rate, _num(snap, rate))
            # per-peer latency/bytes: bounded by fleet size, the evidence
            # hedging decisions and KV-shipping (ROADMAP item 2) read
            for peer, ps in (cache.get("peers") or {}).items():
                ppre = f"cache.{wid}.peer.{peer}."
                self.timeline.record(ppre + "lat_ewma_s",
                                     _num(ps, "lat_ewma_s"))
                self.timeline.record(ppre + "bytes", _num(ps, "bytes"))
                self.timeline.record(ppre + "errors", _num(ps, "errors"))
            pool = snap.get("weightpool") or {}
            for name in WEIGHTPOOL_SERIES:
                if name in pool:
                    self.timeline.record(f"weightpool.{wid}.{name}",
                                         _num(pool, name))
        if self.scaleout is not None:
            # re-plan the multicast tree over fresh holders and publish
            # it where joining workers' tree_hints read it; short TTL so
            # a dead gateway's plan ages out instead of steering forever
            from ..scaleout.coordinator import PLAN_KEY
            plan = self.scaleout.refresh()
            await self.store.set(
                PLAN_KEY, json.dumps(plan.to_dict()),
                ttl=max(int(self.cfg.sample_interval_s * 6), 30))

    # -- engines-section aging (ISSUE 12 satellite) --------------------------

    def filter_engines(self, engines: dict) -> dict:
        """Stamp ``last_seen``/``age_s`` from each heartbeat's wall stamp
        and drop replicas silent > N beats — /api/v1/metrics must not
        serve dead stats until the store TTL. Aged-out replicas also lose
        their goodput delta base (a restart starts a fresh interval)."""
        now = time.time()
        out: dict = {}
        for cid, snap in engines.items():
            ts = _num(snap, "ts")
            age = max(now - ts, 0.0) if ts else 0.0
            if ts and age > self.stale_after_s:
                self.goodput.forget_replica(cid)
                # drop its health/HBM gauges too (ISSUE 14): the dead
                # replica's last verdict must not alert forever, and
                # per-cid gauge series must not accumulate under churn
                from ..observability.health import forget_replica
                forget_replica(cid)
                continue
            row = dict(snap)
            row["last_seen"] = ts
            row["age_s"] = round(age, 3)
            out[cid] = row
        return out

    # -- endpoint payloads ---------------------------------------------------

    def timeline_payload(self, series: str, since: float,
                         limit: Optional[int]) -> dict:
        if not series:
            return {"series_names": self.timeline.series_names(),
                    "capacity": self.timeline.capacity,
                    "samples": self.timeline.sample_count()}
        names = [s.strip() for s in series.split(",") if s.strip()]
        return {"series": self.timeline.query(names, since=since,
                                              limit=limit)}

    def slo_payload(self) -> dict:
        stubs: dict = {}
        known = (self.fleet_router.active_stubs()
                 if self.fleet_router is not None else [])
        signals = (self.fleet_router.signals
                   if self.fleet_router is not None else None)
        for stub in known:
            sid = stub.stub_id
            evaluated = self.evaluator.evaluate(sid)
            row = {"workspace_id": stub.workspace_id,
                   "objectives": evaluated}
            if signals is not None:
                row["slo_pressure"] = signals.slo_pressure(sid)
                row["pressure"] = signals.pressure(sid)
            stubs[sid] = row
        return {
            "objectives": [{
                "name": o.name, "kind": o.kind, "target": o.target,
                "metric": o.metric if o.kind == "latency" else "",
                "attainment": o.attainment if o.kind == "latency" else None,
                "fast_window_s": o.fast_window_s,
                "slow_window_s": o.slow_window_s,
            } for o in self.cfg.objectives],
            "burn_alert": self.cfg.burn_alert,
            "stubs": stubs,
        }

    async def goodput_snapshot(self) -> dict:
        """Per-workspace decomposition joined against usage.py's metered
        chip-second buckets (the billing denominator; the accountant's
        own replica-seconds stand in when the meter reads zero — CPU dev
        fleets meter 0 chips)."""
        workspaces = self.goodput.workspaces()
        metered: dict[str, float] = {}
        window_h = max(int(self.goodput.window_s // 3600), 0) + 1
        now = time.time()
        window_start = now - self.goodput.window_s
        for ws in workspaces:
            total = 0.0
            for h in range(window_h + 1):
                bucket_start = (now // 3600 - h) * 3600
                # prorate by the overlap between the accounting window
                # and the bucket's DATA span (metering stops at `now`
                # for the current bucket; chip-seconds assumed uniform
                # within the span): summing whole buckets would count up
                # to an extra hour of denominator at the top of each
                # hour, understating goodput by up to ~2x on a metered
                # fleet
                span_end = min(now, bucket_start + 3600)
                span = span_end - bucket_start
                overlap = span_end - max(window_start, bucket_start)
                if overlap <= 0 or span <= 0:
                    continue
                hot = await self.store.hgetall(
                    usage_key(ws, bucket_of(bucket_start)))
                if hot:
                    chips = _num(hot, "chip_seconds")
                    if chips > 0:
                        total += chips * min(overlap / span, 1.0)
            metered[ws] = total
        return self.goodput.snapshot(usage_chip_seconds=metered)

    async def metrics_section(self) -> dict:
        return await self.goodput_snapshot()
