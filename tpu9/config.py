"""Layered configuration.

Analogue of the reference's koanf-based ConfigManager (``pkg/common/config.go``)
loading baked-in defaults (``pkg/common/config.default.yaml``) overlaid by a
``CONFIG_PATH`` file then ``CONFIG_JSON``/env vars. tpu9 keeps the same layering
with typed dataclasses instead of a YAML schema: defaults in code → optional
YAML/JSON file at ``TPU9_CONFIG_PATH`` → ``TPU9_CONFIG_JSON`` → ``TPU9_*`` env
overrides (dotted path, e.g. ``TPU9_GATEWAY__HTTP_PORT=8080``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import yaml


@dataclass
class DatabaseConfig:
    # durable backend (sqlite file; ":memory:" for tests)
    path: str = "tpu9.db"
    # hot state bus: "memory" (embedded) or "host:port" of a StateServer
    state_addr: str = "memory"
    state_auth_token: str = ""
    # secrets-at-rest AES key material (production: inject from a KMS);
    # the AES-256 key is sha256 of this string
    secret_key: str = "tpu9-dev-key"


@dataclass
class GatewayConfig:
    host: str = "127.0.0.1"
    http_port: int = 1994
    state_port: int = 14950        # embedded StateServer port (0 = disabled)
    external_url: str = ""
    shutdown_drain_s: float = 30.0
    invoke_base_path: str = ""     # subdomain-less route prefix
    relay_port: int = -1           # cross-host relay (-1 = any free port,
                                   # 0 = disabled); reference: tailscale mesh
    advertise_host: str = ""       # host workers use to dial the relay
                                   # (defaults to gateway.host)


@dataclass
class SchedulerConfig:
    loop_interval_s: float = 0.05   # reference: 50ms batch loop scheduler.go:28
    batch_size: int = 512
    max_retries: int = 12           # with backoff ≈ 1 min of provisioning grace
    backlog_warning_depth: int = 1000
    gang_reservation_ttl_s: float = 30.0


@dataclass
class WorkerPoolConfig:
    name: str = "default"
    mode: str = "process"           # process | runc | gce-tpu
    tpu_type: str = ""              # slice shape this pool provisions ("" = CPU)
    min_free_cpu_millicores: int = 0
    min_free_memory_mb: int = 0
    min_free_tpu_chips: int = 0
    max_workers: int = 10
    runtime: str = "process"
    priority: int = 0
    # gce-tpu pool knobs
    gcp_project: str = ""
    gcp_zone: str = ""
    runtime_version: str = "tpu-ubuntu2204-base"
    reserved: bool = False
    spot: bool = False


@dataclass
class WorkerConfig:
    keepalive_ttl_s: float = 15.0   # reference worker.go:1026 TTL keys
    heartbeat_interval_s: float = 5.0
    idle_shutdown_s: float = 300.0
    start_concurrency: int = 4
    images_dir: str = "/tmp/tpu9/images"
    containers_dir: str = "/tmp/tpu9/containers"
    storage_root: str = "/tmp/tpu9/workspaces"   # volume/object share
    # True when this worker sees the gateway's storage root (same host or a
    # shared mount); False makes workers SYNC volumes from the gateway's
    # object store at container start (multi-host TPU VMs)
    storage_shared: bool = True
    logs_dir: str = "/tmp/tpu9/logs"
    checkpoint_dir: str = "/tmp/tpu9/checkpoints"
    disks_dir: str = "/tmp/tpu9/disks"      # durable-disk host dirs
    # path to the built vcache_preload.so; when set, containers with volume
    # mounts read volume files through the node cache (LD_PRELOAD shim)
    vcache_so: str = ""
    # path to the built t9lazy_preload.so; when set, containers whose image
    # is still streaming gate opens on the lazy-fill fault socket ("" =
    # auto-discover next to vcache_so / the repo's native/build)
    lazy_so: str = ""
    vcache_dir: str = "/tmp/tpu9/vcache"
    failover_max_pending: int = 10
    failover_max_scheduling_latency_ms: float = 5000.0
    # warm weights pool cap (MiB): deserialized host param trees kept
    # alive per node so the Nth replica of a hot model skips cache IO and
    # deserialization (λScale keep-alive tier). 0 disables the pool.
    weight_pool_mb: int = 2048


@dataclass
class CacheConfig:
    enabled: bool = True
    data_dir: str = "/tmp/tpu9/cache"
    max_bytes: int = 32 * 1024**3
    chunk_bytes: int = 4 * 1024**2
    port: int = 0                   # 0 = auto
    replicas: int = 1               # HRW replication factor
    prefetch_window: int = 8
    # images at/above this stream lazily (skeleton-ready + background fill);
    # below it they materialize eagerly with hardlinks
    lazy_threshold_mb: int = 64


@dataclass
class StorageConfig:
    mode: str = "local"             # local | gcs
    local_root: str = "/tmp/tpu9/workspaces"
    gcs_bucket: str = ""


@dataclass
class ImageConfig:
    registry_dir: str = "/tmp/tpu9/registry"   # content-addressed image store
    build_timeout_s: float = 1800.0
    python_version: str = "python3.11"
    # "worker": builds run in scheduled build containers (production);
    # "local": in-process on the gateway host — single-tenant dev ONLY
    build_mode: str = "worker"
    # build-container sizing (reference build pools use dedicated sizing);
    # defaults fit a 1-core dev host — raise for heavy pip graphs
    build_cpu_millicores: int = 1000
    build_memory_mb: int = 2048


@dataclass
class RouterConfig:
    """Fleet inference router (``tpu9/router/`` — ISSUE 2): the front
    door between the gateway invoke paths and engine replicas."""
    enabled: bool = True
    # queue-wait SLO budget: a request queued longer is shed with 503 +
    # Retry-After (effective budget is min(this, stub timeout))
    max_queue_wait_s: float = 30.0
    # per-stub queued-request cap: NEW work past it is shed with 429
    max_queue_depth: int = 256
    # deficit-round-robin quantum (tokens) each tenant earns per ring
    # visit; weights scale it (workspace chip quota / 4, clamped [0.5,16])
    tenant_quantum_tokens: int = 2048
    # in-flight budget for replicas that report no KV headroom (plain
    # endpoints, engines mid-bring-up) — also the cold-start stampede cap
    default_replica_inflight: int = 8
    # hard ceiling on any replica's in-flight budget, however much KV
    # headroom it reports
    max_replica_inflight: int = 64
    # worst-case tokens (prompt + decode) one admitted request may pin —
    # divides reported free KV tokens into an in-flight budget
    kv_tokens_per_request: int = 2048
    # Retry-After floor when shedding with no observed service rate yet
    shed_retry_after_s: float = 1.0
    # prefix-affinity keying granularity (tokens per block) — match the
    # serving EngineConfig.kv_block_size or placement and engine-level
    # prefix reuse diverge
    affinity_block_tokens: int = 16
    affinity_ttl_s: float = 300.0
    # fleet prefix directory (ISSUE 20): fold replicas' heartbeated
    # prefix-key digests + peer-cache residency into placement. False
    # (or TPU9_KV_TIER=0) reverts to affinity-only routing.
    prefix_directory: bool = True
    # graceful scale-down: how long a draining replica may finish its
    # in-flight requests before the container is stopped regardless
    drain_timeout_s: float = 10.0
    # heartbeats older than this are excluded from fleet-wide aggregates
    # (spec acceptance fold — ISSUE 12 stale-replica aging); the store
    # TTL (15 s) only bounds how long a dead hash EXISTS, not whether a
    # fold trusts it. Default = 3 beats of the runner's fixed 2 s
    # cadence, same budget as SloConfig.stale_after_s (router plane vs
    # gateway plane of the one staleness policy)
    heartbeat_stale_s: float = 6.0
    # gray-failure ejection (ISSUE 14): how long one `stalled` health
    # verdict keeps a replica out of routing without renewal. Fresh
    # heartbeats renew (still stalled) or clear (recovered) the mark;
    # expiry is the recovery probe when no observer is folding health
    # (bench driving the router directly). Default = 3 runner beats,
    # aligned with the staleness budgets above.
    health_eject_ttl_s: float = 6.0
    # ---- request survivability (ISSUE 15) ----
    # per-call bound on gateway↔runner control RPCs (flight/profile
    # proxies, ckpt RPC, postmortem forwarding) — the TMO001 audit knob.
    # Generation forwards keep their own request-timeout budget.
    rpc_timeout_s: float = 30.0
    # automatic failover: total attempts per request INCLUDING the first
    # (1 disables retries); jittered exponential backoff between them
    failover_max_attempts: int = 3
    failover_backoff_base_s: float = 0.05
    failover_backoff_max_s: float = 2.0
    # request journal TTL: how long an X-Tpu9-Request-Id entry dedupes
    # client-initiated retries (idempotency window) and how long a
    # completed request's replayable result is retained
    journal_ttl_s: float = 600.0
    # largest completed-response body the journal will retain for replay
    # (bigger results still dedupe, but replay returns a summary)
    journal_body_cap: int = 65536
    # mid-stream failover: max silent gap between SSE chunks from a
    # RESUMABLE stream before it is declared wedged and failed over
    # (env override TPU9_STREAM_GAP_S for chaos tests). Deliberately
    # generous: the gap also covers the pre-first-token window, and a
    # busy replica can legally hold a connected stream quiet for tens of
    # seconds behind engine-side queueing — a too-tight gap turns load
    # into replayed prefills. Non-resumable streams are never gap-bounded
    # (they keep the full request timeout).
    stream_gap_s: float = 90.0
    # ---- disaggregated prefill/decode + KV block shipping (ISSUE 16) ----
    # Off by default: both features change WHERE work lands, so a fleet
    # opts in per deployment. env overrides TPU9_DISAGG / TPU9_KV_SHIP
    # ("1"/"0") for bench and chaos runs.
    disagg_enabled: bool = False
    # a request whose prompt is at least this many tokens is "prefill
    # heavy": routed to the prefill-leaning partition and asked to export
    # its prefill KV for a decode-side adopt
    disagg_prefill_tokens: int = 512
    # fraction of healthy replicas (ceil, always leaving ≥1 decode
    # replica) that lean prefill; partition is deterministic by sorted
    # container id so every router instance agrees without coordination
    disagg_prefill_fraction: float = 0.5
    # KV block shipping for failover resume + drain migration: when on,
    # a resumable stream's exporter emits kv_key events and the failover
    # target tries a block-ship adopt before re-prefilling
    kv_ship_enabled: bool = True
    # streams below this many delivered prompt+output tokens re-prefill
    # instead of shipping (a ship smaller than this costs more than the
    # prefill it saves)
    kv_ship_min_tokens: int = 32


@dataclass
class SloObjectiveConfig:
    """One service-level objective, evaluated per stub at the gateway
    (``tpu9/observability/slo.py`` — ISSUE 12) over fast + slow burn-rate
    windows and served at ``/api/v1/slo``."""
    name: str = ""
    # "latency": fraction of sampled `metric` estimates must stay ≤ target
    #            (attainment is the allowed-good fraction, e.g. 0.99);
    # "availability": 1 − shed rate must stay ≥ target (e.g. 0.999)
    kind: str = "latency"
    metric: str = "ttft_p95_s"     # timeline series suffix (latency kind)
    target: float = 0.0
    attainment: float = 0.99       # latency kind only
    fast_window_s: float = 300.0   # page-now window (5m)
    slow_window_s: float = 3600.0  # sustained-burn window (1h)


def _default_slo_objectives() -> list["SloObjectiveConfig"]:
    return [
        SloObjectiveConfig(name="ttft", kind="latency",
                           metric="ttft_p95_s", target=2.0),
        SloObjectiveConfig(name="availability", kind="availability",
                           target=0.999),
    ]


@dataclass
class SloConfig:
    """Fleet SLO / timeline / goodput layer (ISSUE 12): the in-gateway
    time-series store, burn-rate evaluation, and per-tenant goodput
    accounting behind ``/api/v1/{timeline,slo}`` and ``tpu9 top``."""
    enabled: bool = True
    # gateway sampler tick: router series + SLO evaluation cadence
    sample_interval_s: float = 2.0
    # per-series ring capacity (samples) — the memory bound
    timeline_capacity: int = 512
    timeline_max_series: int = 4096
    timeline_idle_ttl_s: float = 900.0
    # engines-section aging: a replica silent longer than this is
    # dropped from /api/v1/metrics "engines" and fleet-wide aggregates.
    # Default = 3 beats of the llm runner's fixed 2 s pressure-heartbeat
    # cadence; keep it a multiple of that beat (and keep it aligned with
    # RouterConfig.heartbeat_stale_s, the router-plane budget for the
    # same signal)
    stale_after_s: float = 6.0
    # goodput accounting window
    goodput_window_s: float = 3600.0
    # burn-rate threshold that counts as "burning" (and feeds pressure)
    burn_alert: float = 1.0
    # decision ledger caps (ISSUE 19): global ring, per-request index
    # entry cap, records kept per request, and the index idle TTL — the
    # same three-way bounding as the timeline rings above
    decisions_capacity: int = 2048
    decisions_max_requests: int = 1024
    decisions_per_request: int = 32
    decisions_idle_ttl_s: float = 900.0
    objectives: list[SloObjectiveConfig] = field(
        default_factory=_default_slo_objectives)


@dataclass
class ScaleoutConfig:
    """Scale-out plane (``tpu9/scaleout/`` — ISSUE 17): multicast weight
    distribution over the peer-cache tier, execute-while-scaling
    readiness, and the burn-predictive autoscale controller. Env
    overrides follow the standard layering (``TPU9_SCALEOUT__<FIELD>``);
    the master ``TPU9_SCALEOUT`` ("1"/"0") and
    ``TPU9_SCALEOUT_PREDICTIVE`` shortcuts beat the config for bench and
    chaos runs, the TPU9_DISAGG precedent."""
    # distribution tree: on by default — it only biases WHERE a joining
    # replica fetches from (peer edges before HRW fallback), never
    # whether a restore succeeds (source stays the floor)
    enabled: bool = True
    # max children one parent serves per shard group; the planner chains
    # extra joiners into deeper tree levels instead of widening a parent
    tree_fanout: int = 2
    # predictive controller: OFF by default — it changes WHEN capacity is
    # added/removed, so a fleet opts in per deployment (disagg precedent)
    predictive_enabled: bool = False
    # fast-burn slope is fit over this trailing window of SLO samples
    slope_window_s: float = 120.0
    # scale up when the projected fast burn (current + slope × horizon)
    # crosses 1.0 — i.e. the budget WILL start burning before the slow
    # window can trip
    burn_horizon_s: float = 300.0
    # cap on replicas added by one predictive decision
    scale_up_max_step: int = 2
    # scale-down guard: measured bring-up × this safety factor must fit
    # inside the remaining slow-window burn budget, or capacity is held
    bringup_safety: float = 2.0
    # burn samples older than this make the controller HOLD (never grow)
    # — the PR 12 staleness-guard pattern: a dead sampler must not pin
    # the fleet at max. Default = 3 gateway sampler ticks.
    stale_after_s: float = 6.0
    # bring-up estimate used before any coldstart record has been
    # measured for the stub (first scale-down decision of a deployment)
    default_bringup_s: float = 30.0
    # a replica whose heartbeat readiness is below 1.0 admits only
    # requests whose declared weight groups are resident; False admits
    # nothing until the restore completes (the conservative fallback)
    partial_admission: bool = True


@dataclass
class MonitoringConfig:
    metrics_enabled: bool = True
    metrics_push_url: str = ""
    otlp_endpoint: str = ""         # e.g. http://collector:4318 ("" = off)
    otlp_interval_s: float = 15.0
    events_sink: str = "state"      # state | http | none
    events_http_url: str = ""
    log_level: str = "INFO"
    container_log_lines_per_hour: int = 200000


@dataclass
class AppConfig:
    cluster_name: str = "tpu9"
    database: DatabaseConfig = field(default_factory=DatabaseConfig)
    gateway: GatewayConfig = field(default_factory=GatewayConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    worker: WorkerConfig = field(default_factory=WorkerConfig)
    pools: list[WorkerPoolConfig] = field(default_factory=lambda: [WorkerPoolConfig()])
    cache: CacheConfig = field(default_factory=CacheConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    image: ImageConfig = field(default_factory=ImageConfig)
    router: RouterConfig = field(default_factory=RouterConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    scaleout: ScaleoutConfig = field(default_factory=ScaleoutConfig)
    monitoring: MonitoringConfig = field(default_factory=MonitoringConfig)
    debug: bool = False


# typed list-of-dataclass config fields: overlay replaces the whole list,
# each element merged over a fresh default instance
_LIST_FIELDS = {"pools": WorkerPoolConfig, "objectives": SloObjectiveConfig}


def _merge_into(obj: Any, data: dict[str, Any]) -> Any:
    """Recursively overlay dict values onto a dataclass instance."""
    if not dataclasses.is_dataclass(obj):
        return data
    names = {f.name: f for f in dataclasses.fields(obj)}
    for key, value in data.items():
        if key not in names:
            continue
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur) and isinstance(value, dict):
            _merge_into(cur, value)
        elif key in _LIST_FIELDS and isinstance(value, list):
            items = []
            for item in value:
                element = _LIST_FIELDS[key]()
                _merge_into(element, item if isinstance(item, dict) else {})
                items.append(element)
            setattr(obj, key, items)
        else:
            setattr(obj, key, value)
    return obj


def _coerce(cur: Any, raw: str) -> Any:
    if isinstance(cur, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(cur, int):
        return int(raw)
    if isinstance(cur, float):
        return float(raw)
    return raw


def _apply_env(cfg: AppConfig, environ: dict[str, str]) -> None:
    for key, raw in environ.items():
        if not key.startswith("TPU9_") or key in ("TPU9_CONFIG_PATH", "TPU9_CONFIG_JSON"):
            continue
        path = key[len("TPU9_"):].lower().split("__")
        obj: Any = cfg
        ok = True
        for part in path[:-1]:
            if dataclasses.is_dataclass(obj) and hasattr(obj, part):
                obj = getattr(obj, part)
            else:
                ok = False
                break
        leaf = path[-1]
        if ok and dataclasses.is_dataclass(obj) and hasattr(obj, leaf):
            cur = getattr(obj, leaf)
            if dataclasses.is_dataclass(cur):
                # a whole section can't be set from a scalar env var —
                # TPU9_SCALEOUT is the scaleout feature GATE (read by
                # scaleout_on()), not an overlay of the scaleout section
                continue
            setattr(obj, leaf, _coerce(cur, raw))


def load_config(path: Optional[str] = None,
                overrides: Optional[dict[str, Any]] = None,
                environ: Optional[dict[str, str]] = None) -> AppConfig:
    environ = environ if environ is not None else dict(os.environ)
    cfg = AppConfig()
    file_path = path or environ.get("TPU9_CONFIG_PATH")
    if file_path:
        if not Path(file_path).exists():
            # fail fast: an explicitly-configured path that doesn't exist is a
            # misconfiguration, not a request for defaults
            raise FileNotFoundError(f"config file not found: {file_path}")
        with open(file_path) as f:
            data = yaml.safe_load(f) or {}
        _merge_into(cfg, data)
    blob = environ.get("TPU9_CONFIG_JSON")
    if blob:
        _merge_into(cfg, json.loads(blob))
    _apply_env(cfg, environ)
    if overrides:
        _merge_into(cfg, overrides)
    return cfg


# ---------------------------------------------------------------------------
# Shared env-knob accessors (ISSUE 18). A TPU9_* knob read from more
# than one plane goes through exactly one of these, so its default can
# never drift between read sites again — wirecheck's ENV001 pins every
# other module to the reader declared in tpu9/analysis/contracts.toml.


def env_faults_spec() -> str:
    """``TPU9_FAULTS`` chaos spec; empty string = faults plane disarmed.

    Read by the runner serve loop, the cache client and the worker
    keepalive (each arms its own injector lazily so a container without
    the knob never imports the faults plane)."""
    return os.environ.get("TPU9_FAULTS", "")


def env_gateway_url(required: bool = False) -> str:
    """Gateway base url for in-container runners and the SDK."""
    url = os.environ.get("TPU9_GATEWAY_URL", "")
    if required and not url:
        raise KeyError("TPU9_GATEWAY_URL")
    return url


def env_token(required: bool = False) -> str:
    """Workspace-scoped runner/SDK bearer token."""
    token = os.environ.get("TPU9_TOKEN", "")
    if required and not token:
        raise KeyError("TPU9_TOKEN")
    return token


def env_checkpoint_enabled() -> bool:
    """``TPU9_CHECKPOINT_ENABLED=1``: arm the CRIU checkpoint plane."""
    return os.environ.get("TPU9_CHECKPOINT_ENABLED") == "1"


def env_bind_host() -> str:
    """Runner HTTP bind host; the worker sets ``0.0.0.0`` for
    containerised runtimes, host-shared runtimes stay loopback."""
    return os.environ.get("TPU9_BIND_HOST", "127.0.0.1")


def env_criu_bin() -> str:
    """CRIU binary path for checkpoint/restore (cli + localstack)."""
    return os.environ.get("TPU9_CRIU_BIN", "criu")


def env_tpu_gen() -> str:
    """Operator/VM-image declared TPU generation (agent + tpu_manager);
    empty on CPU worker boxes."""
    return os.environ.get("TPU9_TPU_GEN", "")


def env_no_egress() -> bool:
    """``TPU9_NO_EGRESS``: hermetic mode — no outbound network from
    builds or gateway-driven image pulls."""
    return bool(os.environ.get("TPU9_NO_EGRESS"))


def env_scaleout_gate() -> str:
    """Raw ``TPU9_SCALEOUT`` master-gate string ('' = defer to config)."""
    return os.environ.get("TPU9_SCALEOUT", "").strip()


def env_scaleout_predictive_gate() -> str:
    """Raw ``TPU9_SCALEOUT_PREDICTIVE`` gate string ('' = defer)."""
    return os.environ.get("TPU9_SCALEOUT_PREDICTIVE", "").strip()


def env_scaleout_partial_on() -> bool:
    """``TPU9_SCALEOUT_PARTIAL=0`` disables group-hint partial-readiness
    admission; anything else (including unset) leaves it on."""
    return os.environ.get("TPU9_SCALEOUT_PARTIAL", "") != "0"


def env_kv_tier_on() -> bool:
    """``TPU9_KV_TIER=0`` master-gates KV tiering OFF everywhere — the
    engine's host tier, the runner's directory heartbeat extras and the
    router's prefix directory (ISSUE 20). Unset/anything else leaves the
    plane armed; it still only activates where a host pool is sized."""
    return os.environ.get("TPU9_KV_TIER", "") != "0"


def env_kv_host_pool_mb(default: int = 0) -> int:
    """``TPU9_KV_HOST_POOL_MB``: host-DRAM KV tier capacity in MB (0 =
    no host tier). Overrides ``EngineConfig.kv_host_pool_mb`` at engine
    construction; one accessor so every plane sees one default."""
    raw = os.environ.get("TPU9_KV_HOST_POOL_MB", "").strip()
    if not raw:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default
