"""Endpoint runner: the HTTP server the worker execs inside an endpoint
container.

Reference analogue: ``sdk/src/beta9/runner/endpoint.py`` (gunicorn+uvicorn
ASGI host). tpu9's variant is a single aiohttp process (workers>1 scales via
containers, which is where TPU workloads want isolation anyway):

- ``POST /``      → call the user handler with the JSON body as kwargs
- ``GET /health`` → 200 once the handler (and its on_start) is loaded
- ASGI stubs: if the loaded object is an ASGI app, requests are dispatched
  through it instead of the function path.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys

from aiohttp import web

from .common import FunctionHandler, RunnerConfig, dumps, error_payload

log = logging.getLogger("tpu9.runner")


def build_app(cfg: RunnerConfig) -> web.Application:
    handler = FunctionHandler(cfg)
    state = {"ready": False, "inflight": 0}

    async def on_startup(app):
        # load (and run on_start) off the event loop, then flip readiness —
        # the worker's readiness probe gates traffic on this
        def load():
            handler.load()
        await asyncio.to_thread(load)
        state["ready"] = True
        if os.environ.get("TPU9_CHECKPOINT_ENABLED") == "1":
            # handler state is loaded (and saved via ckpt.maybe_restore if
            # the handler opted in) — let the worker snapshot now
            from . import ckpt
            ckpt.mark_ready({"handler": cfg.handler})
        log.info("handler %s ready", cfg.handler)

    async def health(request: web.Request) -> web.Response:
        if not state["ready"]:
            return web.json_response({"ready": False}, status=503)
        return web.json_response({"ready": True, "inflight": state["inflight"]})

    async def invoke(request: web.Request) -> web.Response:
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        try:
            raw = await request.read()
            payload = json.loads(raw) if raw else {}
            if not isinstance(payload, dict):
                payload = {"input": payload}
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        state["inflight"] += 1
        try:
            result = await asyncio.wait_for(handler.call(**payload),
                                            timeout=cfg.timeout_s)
            return web.Response(text=dumps(result),
                                content_type="application/json")
        except asyncio.TimeoutError:
            return web.json_response({"error": "handler timed out"}, status=504)
        except TypeError as exc:
            return web.json_response({"error": f"bad arguments: {exc}"},
                                     status=400)
        except Exception as exc:  # user-code failure → 500 with traceback
            return web.json_response(error_payload(exc), status=500)
        finally:
            state["inflight"] -= 1

    app = web.Application(client_max_size=512 * 1024 * 1024)
    app.on_startup.append(on_startup)
    app.router.add_get("/health", health)
    app.router.add_route("*", "/", invoke)
    app.router.add_route("*", "/{tail:.*}", invoke)
    return app


def main() -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    cfg = RunnerConfig.from_env()
    if not cfg.handler:
        print("TPU9_HANDLER not set", file=sys.stderr)
        sys.exit(2)
    app = build_app(cfg)
    web.run_app(app, host="127.0.0.1", port=cfg.port, print=None,
                handle_signals=True)


if __name__ == "__main__":
    main()
