"""Endpoint runner: the HTTP server the worker execs inside an endpoint
container.

Reference analogue: ``sdk/src/beta9/runner/endpoint.py`` (gunicorn+uvicorn
ASGI host). tpu9's variant is a single aiohttp process (workers>1 scales via
containers, which is where TPU workloads want isolation anyway):

- ``POST /``      → call the user handler with the JSON body as kwargs
- ``GET /health`` → 200 once the handler (and its on_start) is loaded
- @asgi stubs: a handler that IS (or returns) an ASGI app is served through
  the adapter in tpu9.runner.asgi instead of the function path
- @realtime stubs: websocket upgrade on any route; each incoming text/json
  message is passed to the handler and the result sent back
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys

from aiohttp import web

from ..schema import ValidationError
from .common import FunctionHandler, RunnerConfig, dumps, error_payload

log = logging.getLogger("tpu9.runner")


def build_app(cfg: RunnerConfig) -> web.Application:
    handler = FunctionHandler(cfg)
    state = {"ready": False, "inflight": 0, "asgi_app": None}

    async def on_startup(app):
        # load (and run on_start) off the event loop, then flip readiness —
        # the worker's readiness probe gates traffic on this
        def load():
            handler.load()
        await asyncio.to_thread(load)
        if cfg.stub_type == "asgi":
            from .asgi import looks_like_asgi
            target = handler.fn
            if not looks_like_asgi(target):
                # factory style: handler() returns the app
                target = await handler.call()
            state["asgi_app"] = target
        state["ready"] = True
        from ..config import env_checkpoint_enabled
        if env_checkpoint_enabled():
            # handler state is loaded (and saved via ckpt.maybe_restore if
            # the handler opted in) — let the worker snapshot now
            from . import ckpt
            ckpt.mark_ready({"handler": cfg.handler})
        log.info("handler %s ready", cfg.handler)

    async def health(request: web.Request) -> web.Response:
        if not state["ready"]:
            return web.json_response({"ready": False}, status=503)
        return web.json_response({"ready": True, "inflight": state["inflight"]})

    async def realtime(request: web.Request) -> web.StreamResponse:
        """Websocket serving for @realtime stubs (reference RealtimeASGI,
        endpoint/buffer.go:644): one handler call per incoming message."""
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        async for msg in ws:
            if msg.type == web.WSMsgType.BINARY:
                # explicit error beats a silent drop (client would hang
                # awaiting a reply that never comes)
                await ws.send_str(dumps(
                    {"error": "binary frames not supported; send JSON text"}))
                continue
            if msg.type != web.WSMsgType.TEXT:
                continue
            try:
                payload = json.loads(msg.data)
                if not isinstance(payload, dict):
                    payload = {"input": payload}
                result = await handler.call(**payload)
                await ws.send_str(dumps(result))
            except Exception as exc:  # noqa: BLE001 — keep the socket alive
                await ws.send_str(dumps(error_payload(exc)))
        return ws

    async def invoke(request: web.Request) -> web.Response:
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        if (cfg.stub_type == "realtime"
                and request.headers.get("Upgrade", "").lower() == "websocket"):
            return await realtime(request)
        if state["asgi_app"] is not None:
            from .asgi import run_asgi_http
            return await run_asgi_http(state["asgi_app"], request)
        try:
            raw = await request.read()
            payload = json.loads(raw) if raw else {}
            if not isinstance(payload, dict):
                payload = {"input": payload}
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        # argument-binding errors are the CLIENT's 400; a TypeError raised
        # INSIDE the handler body (len(None), wrong internal arity) is a
        # user-code 500 — a broad `except TypeError` conflated them and
        # hid handler crashes from monitoring as "bad arguments"
        if handler.fn is not None:
            import inspect
            try:
                sig = inspect.signature(handler.fn)
                sig.bind(**payload)
            except TypeError as exc:
                return web.json_response(
                    {"error": f"bad arguments: {exc}"}, status=400)
            except ValueError:
                pass               # builtins without introspectable sigs
        state["inflight"] += 1
        try:
            result = await asyncio.wait_for(handler.call(**payload),
                                            timeout=cfg.timeout_s)
            return web.Response(text=dumps(result),
                                content_type="application/json")
        except asyncio.TimeoutError:
            return web.json_response({"error": "handler timed out"}, status=504)
        except ValidationError as exc:
            return web.json_response(exc.to_payload(), status=400)
        except Exception as exc:  # user-code failure → 500 with traceback
            return web.json_response(error_payload(exc), status=500)
        finally:
            state["inflight"] -= 1

    app = web.Application(client_max_size=512 * 1024 * 1024)
    app.on_startup.append(on_startup)
    app.router.add_get("/health", health)
    app.router.add_route("*", "/", invoke)
    app.router.add_route("*", "/{tail:.*}", invoke)
    return app


def main() -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    cfg = RunnerConfig.from_env()
    if not cfg.handler:
        print("TPU9_HANDLER not set", file=sys.stderr)
        sys.exit(2)
    app = build_app(cfg)
    # netns containers (NativeRuntime) are reached over their veth, so the
    # worker sets TPU9_BIND_HOST=0.0.0.0; host-shared runtimes stay loopback
    from ..config import env_bind_host
    web.run_app(app, host=env_bind_host(),
                port=cfg.port, print=None, handle_signals=True)


if __name__ == "__main__":
    main()
