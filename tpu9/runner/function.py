"""Function runner: executes exactly one pinned task, reports, exits.

Reference analogue: ``sdk/src/beta9/runner/function.py:231``. The worker
spawns this with ``TPU9_TASK_ID``; it fetches args from the gateway, runs the
handler, posts the result, and exits 0 (the scheduler/abstraction treat exit
as completion; failures surface through the task result + exit code).

A minimal /health server satisfies the worker's readiness probe (readiness ==
handler loaded, mirroring the endpoint runner).
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys

import aiohttp
from aiohttp import web

from .common import FunctionHandler, RunnerConfig, error_payload, jsonable

log = logging.getLogger("tpu9.runner")


async def run() -> int:
    cfg = RunnerConfig.from_env()
    task_id = os.environ.get("TPU9_TASK_ID", "")
    from ..config import env_gateway_url, env_token
    gateway_url = env_gateway_url()
    token = env_token()
    if not (cfg.handler and task_id and gateway_url):
        print("missing TPU9_HANDLER/TPU9_TASK_ID/TPU9_GATEWAY_URL",
              file=sys.stderr)
        return 2

    handler = FunctionHandler(cfg)
    state = {"ready": False}

    app = web.Application()

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"ready": state["ready"]},
                                 status=200 if state["ready"] else 503)

    app.router.add_get("/health", health)
    app_runner = web.AppRunner(app)
    await app_runner.setup()
    await web.TCPSite(app_runner, "127.0.0.1", cfg.port).start()

    async with aiohttp.ClientSession(
            headers={"Authorization": f"Bearer {token}"}) as session:

        async def api(method: str, path: str, body=None):
            async with session.request(
                    method, gateway_url + path, json=body,
                    timeout=aiohttp.ClientTimeout(total=60)) as resp:
                return resp.status, await resp.json()

        status, payload = await api("GET", f"/rpc/task/{task_id}")
        if status != 200:
            log.error("task fetch failed: %s", payload)
            return 1
        _, claim = await api("POST", f"/rpc/task/{task_id}/claim",
                             {"container_id": cfg.container_id})
        if not claim.get("ok"):
            # task cancelled or owned by a replacement container: user code
            # must not run unowned (duplicate side effects)
            log.info("claim denied for %s; exiting", task_id)
            await app_runner.cleanup()
            return 0

        await asyncio.to_thread(handler.load)
        state["ready"] = True

        try:
            result = await asyncio.wait_for(
                handler.call(*payload.get("args", []),
                             **payload.get("kwargs", {})),
                timeout=cfg.timeout_s)
            body = {"result": jsonable(result)}
            code = 0
        except Exception as exc:  # noqa: BLE001 — user code boundary
            body = {"error": error_payload(exc)["error"]}
            code = 1
        body["container_id"] = cfg.container_id
        try:
            await api("POST", f"/rpc/task/{task_id}/complete", body)
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            log.error("completion report failed: %s", exc)
            return 1
    await app_runner.cleanup()
    return code


def main() -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    sys.exit(asyncio.run(run()))


if __name__ == "__main__":
    main()
