"""Build runner: executes an image build INSIDE a scheduled container.

Reference analogue: ``pkg/abstractions/image/build.go:62,279`` — the build
service schedules a build container on a worker and drives the steps there.
Round 1 ran builds on the gateway host (``asyncio.to_thread`` + subprocess),
which handed tenants arbitrary code execution on the control plane; this
runner restores the reference's isolation: the commands run in THIS
container's sandbox on a worker, and the result is chunked and uploaded to
the gateway's registry over the authenticated image API.

Env contract (set by ImageService when scheduling the build):
  TPU9_BUILD_SPEC    image spec JSON
  TPU9_GATEWAY_URL   gateway base url
  TPU9_TOKEN         runner token (workspace-scoped)
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys

import aiohttp

from ..config import env_gateway_url, env_no_egress, env_token
from ..images import ImageSpec
from ..images.manifest import snapshot_dir


async def amain() -> int:
    spec = ImageSpec.from_dict(json.loads(os.environ["TPU9_BUILD_SPEC"]))
    gateway = env_gateway_url(required=True).rstrip("/")
    token = env_token(required=True)
    image_id = spec.image_id
    scratch = os.path.join(os.getcwd(), "build")
    os.makedirs(scratch, exist_ok=True)
    log_lines: list[str] = []

    def emit(line: str) -> None:
        log_lines.append(line)
        print(line, flush=True)

    async with aiohttp.ClientSession(headers={
            "Authorization": f"Bearer {token}"}) as session:

        async def finish(ok: bool) -> None:
            await session.post(
                f"{gateway}/rpc/image/complete/{image_id}",
                json={"ok": ok, "logs": log_lines[-200:]},
                timeout=aiohttp.ClientTimeout(total=30))

        try:
            env_dir = os.path.join(scratch, "env")
            os.makedirs(env_dir, exist_ok=True)
            oci_env: dict[str, str] = {}

            if spec.from_registry:
                from ..images.oci import (OciClient, aiohttp_transport,
                                          registry_host)
                rootfs = os.path.join(scratch, "rootfs")
                creds = None
                auth = os.environ.get("TPU9_REGISTRY_AUTH", "")
                if auth and ":" in auth:
                    user, _, pw = auth.partition(":")
                    # keyed by the SAME host parse_ref resolves requests to
                    creds = {registry_host(spec.from_registry): (user, pw)}
                # NOT the gateway session: its Authorization header (runner
                # token) must never reach a registry
                transport = aiohttp_transport(credentials=creds)
                try:
                    config = await OciClient(transport).pull(
                        spec.from_registry, rootfs, log_cb=emit)
                finally:
                    await transport.aclose()
                for kv in config.get("Env") or []:
                    k, _, v = kv.partition("=")
                    oci_env[k] = v

            if spec.python_packages:
                site = os.path.join(env_dir, "site-packages")
                os.makedirs(site, exist_ok=True)
                cmd = [sys.executable, "-m", "pip", "install", "--target",
                       site, "--no-compile"]
                wheel_dir = os.environ.get("TPU9_WHEEL_DIR", "")
                if env_no_egress():
                    if not wheel_dir:
                        raise RuntimeError(
                            "package install requested but no network and "
                            "no wheel dir")
                    cmd += ["--no-index", "--find-links", wheel_dir]
                elif wheel_dir:
                    cmd += ["--find-links", wheel_dir]
                cmd += spec.python_packages
                emit(f"pip install {' '.join(spec.python_packages)}")
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=1800)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install failed:\n{proc.stderr[-3000:]}")

            for cmd_line in spec.commands:
                emit(f"RUN {cmd_line}")
                proc = subprocess.run(cmd_line, shell=True, cwd=scratch,
                                      capture_output=True, text=True,
                                      timeout=1800)
                if proc.stdout:
                    emit(proc.stdout[-2000:])
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"command failed ({proc.returncode}): {cmd_line}\n"
                        f"{proc.stderr[-2000:]}")

            emit("snapshotting environment")
            # chunks spool to DISK, not memory: a multi-GB site-packages or
            # OCI rootfs must not scale the build container's RSS with image
            # size (the worker's OOM watcher would kill every attempt)
            spool = os.path.join(os.getcwd(), ".chunk-spool")
            os.makedirs(spool, exist_ok=True)
            digests: list[str] = []

            def put_chunk(data: bytes, digest: str) -> None:
                p = os.path.join(spool, digest)
                if not os.path.exists(p):
                    with open(p, "wb") as f:
                        f.write(data)
                    digests.append(digest)

            manifest = snapshot_dir(scratch, put_chunk=put_chunk)
            manifest.image_id = image_id
            manifest.python_version = spec.python_version
            manifest.kind = "oci" if spec.from_registry else "env"
            # precedence: OCI config env < spec env (user declarations win)
            manifest.env = {**oci_env, **spec.env}
            if spec.python_packages:
                manifest.env.setdefault("TPU9_IMAGE_SITE",
                                        "env/site-packages")

            emit(f"uploading {len(digests)} chunks")
            sem = asyncio.Semaphore(8)

            async def upload(digest: str) -> None:
                async with sem:   # bounded: ≤8 chunks in memory at once
                    with open(os.path.join(spool, digest), "rb") as f:
                        data = f.read()
                    async with session.post(
                            f"{gateway}/rpc/image/chunk/{digest}",
                            data=data,
                            timeout=aiohttp.ClientTimeout(total=300)) as resp:
                        if resp.status != 200:
                            raise RuntimeError(
                                f"chunk upload {digest[:12]} failed: "
                                f"{resp.status} {await resp.text()}")

            await asyncio.gather(*[upload(d) for d in digests])
            async with session.post(
                    f"{gateway}/rpc/image/manifest/{image_id}",
                    data=manifest.to_json(),
                    timeout=aiohttp.ClientTimeout(total=300)) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"manifest upload failed: {resp.status} "
                        f"{await resp.text()}")
            emit(f"built {image_id}: {len(manifest.files)} files, "
                 f"{manifest.total_bytes >> 20} MiB")
            await finish(True)
            return 0
        except Exception as exc:   # noqa: BLE001 — report, don't crash silent
            emit(f"BUILD FAILED: {exc}")
            try:
                await finish(False)
            except Exception:
                pass
            return 1


def main() -> None:
    sys.exit(asyncio.run(amain()))


if __name__ == "__main__":
    main()
