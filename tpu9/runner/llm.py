"""LLM serving runner: hosts an InferenceEngine behind the endpoint protocol.

This is the runner image for baseline configs #2/#4 (Llama on v5e-1 /
Llama-70B TP on v5e-8): the worker spawns it with a handler that returns
either an :class:`tpu9.serving.InferenceEngine` or a ``(params, cfg)`` pair /
preset name; it serves:

- ``POST /``            {"tokens": [...], "max_new_tokens": n} → {"tokens": [...]}
- ``POST /generate``    same (alias)
- ``GET /health``       readiness + engine stats

and heartbeats token-pressure/active-streams to the gateway so the
token-pressure autoscaler and the prefix-affinity router see real engine
load (reference pod/llm.go's per-container snapshots).

Multi-host gangs call ``initialize_multihost()`` before touching jax, so a
v5p-64 deployment's 16 runners join one jax.distributed job.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
from typing import Optional

import aiohttp
from aiohttp import web

from ..config import (env_bind_host, env_checkpoint_enabled,
                      env_faults_spec, env_gateway_url, env_kv_tier_on,
                      env_token)
from .common import FunctionHandler, RunnerConfig, error_payload

log = logging.getLogger("tpu9.runner")


def _build_engine(obj):
    """Accept an InferenceEngine, a (params, cfg) pair, or a preset name."""
    from ..serving import EngineConfig, InferenceEngine
    if hasattr(obj, "generate") and hasattr(obj, "stats"):
        return obj
    if isinstance(obj, tuple) and len(obj) in (2, 3):
        params, cfg = obj[0], obj[1]
        ecfg = obj[2] if len(obj) == 3 else EngineConfig()
        return InferenceEngine(params, cfg, ecfg)
    if isinstance(obj, str):
        # preset name, optionally "-int8"-suffixed (weight-only quantized).
        # compile-ahead: the serving graphs AOT-compile from the preset's
        # abstract shapes concurrently with weight materialization, so the
        # post-build warmup() below dispatches precompiled executables
        # instead of serializing XLA behind the weight load.
        # TPU9_SPEC_LEN opts the deployment into self-speculative decoding
        # (prompt-lookup drafts, ISSUE 5) without a handler change —
        # greedy output is identical either way, only tokens/sec moves.
        # TPU9_QUANTIZE / TPU9_KV_QUANT (e.g. "int8") opt into quantized
        # serving (ISSUE 6): int8 weights / int8 paged KV pool — same
        # no-handler-change contract, per-deployment.
        from ..serving.presets import load_engine
        spec_len = int(os.environ.get("TPU9_SPEC_LEN", "0") or 0)
        quantize = os.environ.get("TPU9_QUANTIZE", "") or None
        kv_quant = os.environ.get("TPU9_KV_QUANT", "") or None
        return load_engine(obj, compile_ahead=True, spec_len=spec_len,
                           quantize=quantize, kv_quant=kv_quant)
    raise TypeError(f"handler must return an engine, (params, cfg) or a "
                    f"preset name; got {type(obj)}")


def _kv_transport():
    """CacheClient for shipped paged-KV blocks (ISSUE 16), or None when
    the deployment has no kv cache plane. TPU9_KV_CACHE_DIR points the
    replica at its content-addressed store (a shared dir in dev makes
    every ship a local hit); TPU9_CACHE_PEERS ("host:port,host:port")
    adds the HRW/hedged peer tier. The engine itself never sees this —
    the runner moves bytes between transport and engine, keeping the
    serving stack transport-free (BND001)."""
    cache_dir = os.environ.get("TPU9_KV_CACHE_DIR", "")
    if not cache_dir:
        return None
    from ..cache.client import CacheClient
    from ..cache.store import DiskStore
    peers = [p.strip() for p in
             os.environ.get("TPU9_CACHE_PEERS", "").split(",") if p.strip()]

    async def peer_fn():
        return peers

    return CacheClient(DiskStore(cache_dir), peer_fn,
                       self_address=os.environ.get("TPU9_CACHE_SELF", ""))


async def amain() -> None:
    cfg = RunnerConfig.from_env()
    gateway_url = env_gateway_url()
    token = env_token()

    # fault-injection plane (ISSUE 15): env-gated, None in production.
    # The import is lazy on purpose — tpu9.testing.faults is restricted
    # to the declared hook sites (boundaries.toml) and a production
    # container without TPU9_FAULTS never imports it.
    faults = None
    if env_faults_spec():
        from ..testing.faults import FaultPlane
        faults = FaultPlane.from_env()
        log.warning("fault plane ACTIVE: %s", sorted(faults.specs))

    # multi-host gang? join the slice-wide jax.distributed job first
    from ..parallel.distributed import initialize_multihost
    initialize_multihost()

    # kvwire transport (ISSUE 16): optional, env-gated — block shipping
    # (disagg handoff / drain migration / failover resume) degrades to
    # plain re-prefill wherever this is None
    kv_client = _kv_transport()

    # KV-motion spans + migration decision records (ISSUE 19): block
    # movement shows up inline with the request's prefill/decode spans,
    # and the adopt/drain verdicts ride the pressure heartbeat to the
    # gateway's decision API exactly like engine spans do
    from ..observability.decisions import ledger as decision_ledger, rej
    from ..observability.trace import tracer as _tracer

    # "beat": request completions set this to nudge the pressure loop into
    # an immediate heartbeat, so a completed request's engine spans ship
    # BEFORE an aggressive scale-to-zero can kill the replica (ISSUE 8)
    state = {"ready": False, "engine": None, "beat": asyncio.Event()}

    async def health(request: web.Request) -> web.Response:
        if not state["ready"]:
            return web.json_response({"ready": False}, status=503)
        stats = state["engine"].stats()
        if stats.get("engine_dead"):
            # the serve loop died: stop advertising ready or the gateway
            # keeps routing requests into a black hole
            return web.json_response({"ready": False, **stats}, status=503)
        return web.json_response({"ready": True, **stats})

    def _trace_ctx(request: web.Request):
        """(trace_id, parent_span_id) from the gateway-minted
        X-Tpu9-Trace header, or None — the engine records its request/
        prefill/decode-window spans under this remote parent (ISSUE 8)."""
        raw = request.headers.get("X-Tpu9-Trace", "")
        if not raw or ":" not in raw:
            return None
        trace_id, _, parent = raw.partition(":")
        return (trace_id, parent) if trace_id else None

    def _budget_s(request: web.Request):
        """Remaining deadline budget from the gateway's X-Tpu9-Budget-S
        header (relative seconds — relative survives clock skew across
        the RPC boundary; the gateway deducts spent budget per attempt).
        None = no deadline."""
        raw = request.headers.get("X-Tpu9-Budget-S", "")
        if not raw:
            return None
        try:
            return float(raw)
        except ValueError:
            return None

    import time as _now

    async def _kv_adopt(adopt, trace=None) -> None:
        """Best-effort pre-generate adopt of shipped KV blocks: fetch by
        key, splice into the pool, register the exporter's prefix. Every
        failure path (no transport, fetch miss, induced kv_ship_error,
        malformed payload, pool pressure) degrades to plain re-prefill —
        the request itself NEVER fails because a ship did."""
        key = str((adopt or {}).get("key") or "")
        if not key:
            return
        tid, parent = trace or ("", "")
        t0w, t0m = _now.time(), _now.monotonic()
        want_tokens = int((adopt or {}).get("n_tokens") or 0)

        def _verdict(outcome: str, reason: str = "") -> None:
            # kv.adopt span on the request's trace tree + the runner half
            # of the migration decision chain (ISSUE 19) — both ride the
            # pressure heartbeat to the gateway
            if tid:
                _tracer.record_span(
                    "kv.adopt", tid, parent, t0w, t0m,
                    attrs={"key": key[:16], "outcome": outcome,
                           "n_tokens": want_tokens},
                    status="ok" if outcome == "adopted" else "error")
            decision_ledger.record(
                "migration", "adopt", request_id=tid, chosen=outcome,
                rejected=[] if outcome == "adopted"
                else [rej("block_ship", reason)],
                signals={"n_tokens": want_tokens,
                         "container_id": cfg.container_id})

        engine = state["engine"]
        if kv_client is None:
            engine.note_kvwire_fallback()
            _verdict("re_prefill", "no_kv_transport")
            return
        if faults is not None and faults.fire("kv_ship_error"):
            log.warning("fault plane: induced kv ship error (adopt %s)",
                        key[:12])
            engine.note_kvwire_fallback()
            _verdict("re_prefill", "induced_kv_ship_error")
            return
        t0 = _now.monotonic()
        try:
            data = await kv_client.get_kv(key)
        except Exception as exc:    # noqa: BLE001 — transport, not request
            log.warning("kv ship fetch failed (%s): %s", key[:12], exc)
            data = None
        if data is None:
            engine.note_kvwire_fallback()
            _verdict("re_prefill", "fetch_miss")
            return
        try:
            if engine.adopt_kv(data):   # False self-counts the fallback
                engine.note_kvwire_ship(_now.monotonic() - t0)
                _verdict("adopted")
            else:
                _verdict("re_prefill", "adopt_declined")
        except Exception as exc:    # noqa: BLE001 — KvWireError and kin
            log.warning("kv adopt rejected (%s): %s", key[:12], exc)
            engine.note_kvwire_fallback()
            _verdict("re_prefill", "adopt_rejected")

    async def _kv_publish(tokens: list, trace=None) -> Optional[dict]:
        """export_after_prefill: serialize the prefix-cached blocks the
        prefill just inserted and publish them under the kv: namespace.
        Returns the ``{"kv_key", "n_tokens"}`` announcement (the SSE
        event body / JSON response fields), or None when there is
        nothing to ship."""
        if kv_client is None:
            return None
        tid, parent = trace or ("", "")
        engine = state["engine"]
        try:
            t0w, t0m = _now.time(), _now.monotonic()
            payload = engine.export_prefix_kv(tokens)
            if payload is None:
                return None
            from ..serving.kvwire import decode_header
            header, _ = decode_header(payload)
            n_tok = int(header.get("n_tokens", 0))
            if tid:
                # kv.export: serialize time; kv.ship: transport time —
                # two spans so a slow ship is distinguishable from a
                # slow pool walk on the trace tree (ISSUE 19)
                _tracer.record_span(
                    "kv.export", tid, parent, t0w, t0m,
                    attrs={"n_tokens": n_tok, "bytes": len(payload)})
            t1w, t1m = _now.time(), _now.monotonic()
            digest = await kv_client.put_kv(payload)
            engine.note_kvwire_ship(_now.monotonic() - t1m)
            if tid:
                _tracer.record_span(
                    "kv.ship", tid, parent, t1w, t1m,
                    attrs={"key": digest[:16], "n_tokens": n_tok,
                           "bytes": len(payload)})
            return {"kv_key": digest, "n_tokens": n_tok}
        except Exception as exc:    # noqa: BLE001 — ship is best-effort
            log.warning("kv export/publish failed: %s", exc)
            return None

    async def generate(request: web.Request) -> web.StreamResponse:
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        if faults is not None and faults.fire("rpc_error"):
            # induced RPC transport error: the gateway's forward sees a
            # mid-request connection reset, exactly like a NIC/proxy blip
            if request.transport is not None:
                request.transport.close()
            raise ConnectionResetError(
                "tpu9.testing.faults: induced rpc transport error")
        try:
            payload = json.loads(await request.read() or b"{}")
            tokens = payload.get("tokens") or payload.get("prompt_tokens")
            if not isinstance(tokens, list) or not tokens:
                return web.json_response(
                    {"error": "body must include 'tokens': [int, ...]"},
                    status=400)
            prompt = [int(t) for t in tokens]
            max_new = int(payload.get("max_new_tokens", 32))
            trace = _trace_ctx(request)
            budget = _budget_s(request)
            if budget is not None and budget <= 0:
                # past budget at the door: never even enqueue (the
                # engine would reject it too; answering here saves the
                # queue round-trip)
                return web.json_response(
                    {"error": "deadline_exceeded: budget exhausted "
                              "before dispatch"}, status=504)
            # kvwire request modes (ISSUE 16): adopt shipped blocks
            # BEFORE admission (the prefix cache then serves them to the
            # ordinary prefix-reuse path); export after prefill when the
            # router asked for a disagg handoff
            if payload.get("adopt_kv"):
                await _kv_adopt(payload.get("adopt_kv"), trace)
            kv_export = bool(payload.get("kv_export")
                             or payload.get("export_after_prefill"))
            if payload.get("stream") or \
                    "text/event-stream" in request.headers.get("Accept", ""):
                return await _generate_sse(request, prompt, max_new, trace,
                                           budget, kv_export=kv_export)
            out = await state["engine"].generate(prompt,
                                                 max_new_tokens=max_new,
                                                 trace=trace,
                                                 budget_s=budget)
            state["beat"].set()
            resp = {"tokens": out}
            if kv_export:
                resp.update(await _kv_publish(prompt, trace) or {})
            return web.json_response(resp)
        except TimeoutError as exc:
            # engine deadline expiry (ISSUE 15): 504, not 400/500 — the
            # gateway must neither blame the request nor retry it
            if "deadline_exceeded" in str(exc):
                return web.json_response({"error": str(exc)}, status=504)
            return web.json_response(error_payload(exc), status=500)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        except Exception as exc:  # noqa: BLE001
            return web.json_response(error_payload(exc), status=500)

    async def _generate_sse(request: web.Request, prompt: list,
                            max_new: int, trace=None, budget=None,
                            kv_export: bool = False) -> web.StreamResponse:
        """Server-sent token stream: one `data: {"token": N}` event per
        generated token, then `data: {"done": true, "tokens": [...]}` —
        relayed incrementally by the gateway's streaming proxy. Dict
        items in the request queue (drain-migration ``kv_key``
        announcements) pass through as their own events."""
        req = await state["engine"].generate(prompt, max_new_tokens=max_new,
                                             stream=True, trace=trace,
                                             budget_s=budget)
        sr = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream",
                                 "Cache-Control": "no-cache",
                                 "X-Accel-Buffering": "no"})
        await sr.prepare(request)
        out: list = []
        # export_after_prefill (ISSUE 16): announce once, right after the
        # first token proves prefill (and its prefix-cache insert) is done
        kv_pending = kv_export and kv_client is not None
        try:
            while True:
                tok = await req.queue.get()
                if tok is None:
                    break
                if isinstance(tok, dict):
                    await sr.write(f"data: {json.dumps(tok)}\n\n".encode())
                    continue
                out.append(tok)
                if faults is not None and faults.fire("proc_exit",
                                                      tokens=len(out)):
                    # hard replica death mid-stream: the strongest chaos
                    # case — transport cut, no error event, no goodbye
                    log.warning("fault plane: proc_exit after %d tokens",
                                len(out) - 1)
                    os._exit(17)
                await sr.write(
                    f"data: {json.dumps({'token': tok})}\n\n".encode())
                if kv_pending:
                    kv_pending = False
                    ev = await _kv_publish(prompt, trace)
                    if ev:
                        await sr.write(
                            f"data: {json.dumps(ev)}\n\n".encode())
            if req.error:
                await sr.write(
                    f"data: {json.dumps({'error': req.error})}\n\n".encode())
            else:
                await sr.write(
                    f"data: {json.dumps({'done': True, 'tokens': out})}\n\n"
                    .encode())
            await sr.write_eof()
            state["beat"].set()
        except ConnectionResetError:
            # client went away: tell the ENGINE — otherwise the slot keeps
            # decoding the full budget into a queue nobody reads, pinning
            # batch capacity with dead work
            state["engine"].cancel_request(req)
        except asyncio.CancelledError:
            # server teardown / disconnect cancellation: same engine-side
            # cleanup, but the cancellation must still propagate
            state["engine"].cancel_request(req)
            raise
        return sr

    async def flight(request: web.Request) -> web.Response:
        """Flight-recorder tail (ISSUE 8): the gateway's /api/v1/flight
        proxies here through the request buffer."""
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        try:
            limit = int(request.query.get("limit", 256))
            since_seq = int(request.query.get("since_seq", 0))
        except ValueError:
            return web.json_response(
                {"error": "limit/since_seq must be integers"}, status=400)
        return web.json_response({
            "container_id": cfg.container_id,
            "flight": state["engine"].flight_records(
                limit=limit, since_seq=since_seq)})

    async def profile(request: web.Request) -> web.Response:
        """Arm jax.profiler for the next N engine windows (ISSUE 8);
        returns the dump path on THIS replica immediately."""
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        try:
            payload = json.loads(await request.read() or b"{}")
            out = state["engine"].arm_profile(
                windows=int(payload.get("windows", 8)),
                out_dir=str(payload.get("out_dir", "") or ""))
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        out["container_id"] = cfg.container_id
        return web.json_response(out)

    async def drain(request: web.Request) -> web.Response:
        """Graceful-drain migration (ISSUE 16): export every in-flight
        stream's full-block KV prefix, publish it under the kv:
        namespace, and push a ``kv_key`` event into each live SSE stream
        — so when this replica stops, the gateway resumes those
        generations on a survivor by block ship instead of re-prefill.
        Best-effort per stream: a failed export just means that stream
        falls back to re-prefill at failover."""
        if not state["ready"]:
            return web.json_response({"error": "not ready"}, status=503)
        try:
            payload = json.loads(await request.read() or b"{}")
        except ValueError:
            payload = {}
        min_tokens = int(payload.get("min_tokens", 32))
        engine = state["engine"]
        migrated: dict = {}
        if kv_client is not None:
            from ..serving.kvwire import decode_header
            for req in engine.active_stream_requests():
                tid, parent = req.trace or ("", "")
                if len(req.prompt) + len(req.generated) < min_tokens:
                    decision_ledger.record(
                        "migration", "drain_export", request_id=tid,
                        chosen="skip",
                        rejected=[rej("block_ship",
                                      f"under_min_tokens_{min_tokens}")],
                        signals={"tokens": len(req.prompt)
                                 + len(req.generated),
                                 "container_id": cfg.container_id})
                    continue
                t0w, t0m = _now.time(), _now.monotonic()
                try:
                    blob = engine.export_request_kv(req.request_id)
                    if blob is None:
                        continue
                    header, _ = decode_header(blob)
                    t0 = _now.monotonic()
                    digest = await kv_client.put_kv(blob)
                    engine.note_kvwire_ship(_now.monotonic() - t0)
                except Exception as exc:    # noqa: BLE001 — per-stream
                    log.warning("drain export failed (%s): %s",
                                req.request_id, exc)
                    decision_ledger.record(
                        "migration", "drain_export", request_id=tid,
                        chosen="re_prefill",
                        rejected=[rej("block_ship", type(exc).__name__)],
                        signals={"container_id": cfg.container_id})
                    continue
                ev = {"kv_key": digest,
                      "n_tokens": int(header.get("n_tokens", 0))}
                if tid:
                    # kv.drain: the drain re-export's block motion on the
                    # stream's own trace tree (ISSUE 19)
                    _tracer.record_span(
                        "kv.drain", tid, parent, t0w, t0m,
                        attrs={"key": digest[:16], "bytes": len(blob),
                               "n_tokens": ev["n_tokens"]})
                decision_ledger.record(
                    "migration", "drain_export", request_id=tid,
                    chosen="block_ship",
                    signals={"n_tokens": ev["n_tokens"],
                             "bytes": len(blob),
                             "container_id": cfg.container_id})
                migrated[req.request_id] = ev
                req.queue.put_nowait(dict(ev))
        return web.json_response({"container_id": cfg.container_id,
                                  "migrated": migrated,
                                  "kv_transport": kv_client is not None})

    app = web.Application(client_max_size=64 * 1024 * 1024)
    app.router.add_get("/health", health)
    app.router.add_post("/", generate)
    app.router.add_post("/generate", generate)
    app.router.add_get("/flight", flight)
    app.router.add_post("/profile", profile)
    app.router.add_post("/drain", drain)
    runner = web.AppRunner(app)
    await runner.setup()
    await web.TCPSite(runner, env_bind_host(),
                      cfg.port).start()

    # build the engine off the loop (model init / weight load can be slow)
    # — under one runner.bringup span carrying the container's minted
    # trace id (ISSUE 13), so the handler's restore.load, load_engine's
    # compile_ahead/bind and the warmup below merge with the worker's
    # restore.request tree into ONE bring-up trace at /api/v1/traces
    # (spans ship on the pressure heartbeat; the gateway stamps tenancy)
    import time as _time

    from ..observability.trace import tracer
    from ..observability import coldstart as _cs
    handler = FunctionHandler(cfg)
    t_bring = _time.monotonic()
    with tracer.span(_cs.SPAN_BRINGUP,
                     trace_id=os.environ.get("TPU9_TRACE_ID", ""),
                     attrs={"container_id": cfg.container_id,
                            "restored":
                            os.environ.get("TPU9_RESTORED", "0")}):
        result = await handler.call()
        engine = _build_engine(result)
        # handler wall INCLUDES the engine build (load_engine's weight
        # materialization + overlapped precompile live inside it);
        # warmup_s below is only the pre-readiness graph warmup
        t_load_done = _time.monotonic()
        # compile every serving graph BEFORE readiness: the first user
        # request must never pay a multi-second XLA compile (readiness ==
        # serveable)
        with tracer.span(_cs.SPAN_WARMUP):
            timings = await asyncio.get_event_loop().run_in_executor(
                None, engine.warmup)
        t_warm_done = _time.monotonic()
    ahead = getattr(engine, "compile_ahead_timings", None)
    if ahead:
        log.info("compile-ahead (overlapped with weight load): %s",
                 {k: round(v, 2) for k, v in ahead.items()})
    log.info("engine warmup: %s",
             {k: round(v, 2) for k, v in timings.items()})
    if faults is not None:
        # serve-loop fault hooks (crash / stall) patch the INSTANCE —
        # the plane never imports the serving stack
        faults.instrument_engine(engine)
    await engine.start()
    state["engine"] = engine
    state["ready"] = True
    # runner-half coldstart record fields (the worker half rides the
    # coldstart:<cid> store key): handler wall covers restore.load +
    # load_engine; ready_s is the whole bring-up to serveable
    bringup = dict(getattr(engine, "bringup", None) or {})
    bringup["handler_s"] = round(t_load_done - t_bring, 4)
    bringup["warmup_s"] = round(t_warm_done - t_load_done, 4)
    bringup["ready_s"] = round(_time.monotonic() - t_bring, 4)
    bringup["restored"] = int(os.environ.get("TPU9_RESTORED", "0") == "1")
    engine.bringup = bringup
    if env_checkpoint_enabled():
        from . import ckpt
        ckpt.mark_ready({"handler": cfg.handler})
    log.info("llm engine ready")

    async def pressure_loop() -> None:
        if not gateway_url:
            return
        rejected_logged = False
        from ..utils.aio import event_wait
        # span-ship watermark (ISSUE 8): MONOTONIC (an NTP step must not
        # gate shipping), and only advances after a heartbeat the gateway
        # ACCEPTED — a gateway blip retries the same window next beat
        # instead of silently dropping engine spans (bounded by the
        # tracer ring, same honesty as the worker/OTLP paths)
        last_span_ship = 0.0
        # decision-record ship cursor (ISSUE 19): seq-keyed, same
        # retry-don't-drop contract — a rejected beat re-ships the window
        last_dec_ship = 0
        # kv-tier delta cursor (ISSUE 20): the eviction/spill journal
        # ships as a heartbeat delta and the cursor only advances on an
        # ACCEPTED beat — a gateway blip re-ships the same retractions
        # instead of leaving the directory believing a prefix survived
        last_tier_delta = 0
        kvtier_hb = env_kv_tier_on()
        # peer-cache publications this replica made ((key_hex16, digest,
        # n_tokens)) — re-advertised each beat, bounded
        peer_pub: list = []
        from ..observability.trace import RING_CAP, tracer
        # replica health plane (ISSUE 14): the watchdog classifies the
        # engine's liveness watermark each beat and the verdict rides the
        # heartbeat — this loop is exactly the "runner still alive while
        # the serve loop is wedged" side of a gray failure, so it must
        # never await the engine, only read its stats dict
        from ..observability.health import (EngineWatchdog, WatchdogConfig,
                                            build_postmortem)
        watchdog = EngineWatchdog(WatchdogConfig.from_env())
        beat_s = float(os.environ.get("TPU9_PRESSURE_INTERVAL_S", "")
                       or 2.0)
        crash_shipped = False
        pending_pm: Optional[dict] = None
        # post-mortem ship retry budgets (ISSUE 15 satellite: the shared
        # backoff helper replaces the hand-rolled 5/30 counters). The
        # heartbeat paces the loop, so the DELAY side is unused — only
        # the attempt accounting and give-up classification.
        from ..utils.backoff import BackoffPolicy, RetryState
        pm_retry = RetryState(BackoffPolicy(base_s=beat_s, jitter=0.0),
                              permanent_max=5, transient_max=30)
        async with aiohttp.ClientSession(
                headers={"Authorization": f"Bearer {token}"}) as session:
            while True:
                try:
                    stats = engine.stats()
                    # fleet-router / observability extras (ISSUE 2
                    # satellite): queue depth, KV headroom, prefix-cache
                    # hit rate — flat scalars only (the pressure table is
                    # a store hash; nested dicts don't round-trip)
                    extra = {"queued": stats.get("queued", 0)}
                    for k in ("kv_blocks_free", "kv_blocks_used",
                              "kv_blocks_reserved", "kv_block_size",
                              # int8 KV pool flag (ISSUE 6): the block
                              # counts already reflect the 2x pool, this
                              # labels WHY a replica reports double
                              "kv_quant",
                              # speculative-decoding acceptance (ISSUE 5):
                              # the router aggregates these into the
                              # fleet-wide tpu9_router_spec_* signals
                              "spec_proposed", "spec_accepted",
                              "spec_acceptance_rate",
                              # serving submesh (ISSUE 9): which topology
                              # this replica runs and its worst-chip live
                              # HBM — the fleet view's multichip evidence
                              "topo_tp", "topo_fsdp", "topo_n_chips",
                              "hbm_used_gb_per_chip",
                              # HBM watermarks + liveness watermark
                              # (ISSUE 14): peak/predicted/limit make
                              # planner-vs-reality drift graphable; the
                              # ages are the watchdog's raw evidence,
                              # surfaced so `tpu9 top` / the black box
                              # can show WHY a verdict was reached
                              "hbm_peak_gb_per_chip",
                              "hbm_predicted_gb_per_chip",
                              "hbm_limit_gb_per_chip",
                              "windows_processed",
                              "last_dispatch_age_s",
                              "last_progress_age_s",
                              # recompile sentinel (ISSUE 11): a non-zero
                              # post_warmup count is a mid-serve XLA
                              # compile — the closed-signature invariant
                              # broke at runtime
                              "graph_compiles",
                              "graph_compiles_post_warmup",
                              # fleet timeline + goodput accounting
                              # (ISSUE 12): windowed tokens/sec, the
                              # cumulative counters the gateway's
                              # accountant differentiates, and the decode
                              # physics constants the control plane
                              # prices MFU/MBU from
                              "tokens_per_sec", "tokens_generated",
                              "graph_compile_stall_s",
                              "decode_bytes_per_token_per_chip",
                              "decode_flops_per_token_per_chip",
                              "device_kind"):
                        if k in stats:
                            extra[k] = stats[k]
                    pc = stats.get("prefix_cache")
                    if isinstance(pc, dict):
                        hits = pc.get("hits", 0)
                        misses = pc.get("misses", 0)
                        extra["prefix_hits"] = hits
                        extra["prefix_misses"] = misses
                        extra["prefix_hit_rate"] = (
                            hits / (hits + misses) if hits + misses else 0.0)
                    # cold-start decomposition (ISSUE 13): the runner half
                    # of the per-replica readiness record — flat
                    # coldstart_* scalars merged by /api/v1/coldstart
                    for k, v in stats.items():
                        if k.startswith("coldstart_"):
                            extra[k] = v
                    # kvwire (ISSUE 16): block-ship counters + latency
                    # percentiles — one prefix covers the whole family
                    # (engine.stats() keeps them flat on purpose)
                    for k, v in stats.items():
                        if k.startswith("kvwire_"):
                            extra[k] = v
                    # kv tiering (ISSUE 20): occupancy/paging counters
                    # (same one-startswith-loop contract as kvwire_*),
                    # then the directory summaries: a bounded top-K
                    # prefix-key digest, the eviction-delta retractions,
                    # and this replica's peer-cache publications — never
                    # full key lists
                    for k, v in stats.items():
                        if k.startswith("kvtier_"):
                            extra[k] = v
                    tier_hi = last_tier_delta
                    if kvtier_hb and state["engine"] is not None:
                        # serving-plane kv_tier choices (spill scoring,
                        # up-page pulls, lost-copy recomputes) arrive as
                        # plain journal dicts; the RUNNER records them —
                        # the serving plane must not import the ledger
                        # (BND001), same flow as spans/health verdicts
                        for d in state["engine"].drain_kvtier_decisions():
                            decision_ledger.record(
                                "kv_tier", d.pop("decision", "spill"), **d)
                        if kv_client is not None:
                            for khex, payload, n_tok in \
                                    state["engine"].drain_kv_spills():
                                try:
                                    t0m = _now.monotonic()
                                    digest = await kv_client.put_kv(
                                        payload)
                                    state["engine"].note_kvwire_ship(
                                        _now.monotonic() - t0m)
                                    peer_pub.append(
                                        (khex, digest, n_tok))
                                except Exception as exc:  # noqa: BLE001
                                    log.warning(
                                        "kv tier peer spill failed: %s",
                                        exc)
                            del peer_pub[:-32]
                        digest_s = state["engine"].kvtier_digest()
                        if digest_s:
                            extra["kvtier_keys"] = digest_s
                        deltas, tier_hi = state["engine"].kvtier_deltas(
                            last_tier_delta)
                        lost = [hx for kind, hx in deltas
                                if kind in ("evict", "peer")]
                        if lost:
                            extra["kvtier_evicted"] = ",".join(lost)
                        if peer_pub:
                            extra["kvtier_peer"] = ",".join(
                                f"{hx}:{dig}:{nt}"
                                for hx, dig, nt in peer_pub)
                    # scale-out readiness (ISSUE 17): per-group bind
                    # progress of a streaming restore — the router's
                    # partial-readiness admission reads these off the
                    # pressure hash, the coordinator off the heartbeat
                    for k, v in stats.items():
                        if k.startswith("scaleout_"):
                            extra[k] = v
                    # latency decomposition (ISSUE 8): per-phase p50/p95
                    # flat scalars → /api/v1/metrics "engines" section
                    for k, v in (stats.get("latency") or {}).items():
                        extra[k] = v
                    fl = stats.get("flight")
                    if isinstance(fl, dict):
                        extra["flight_records"] = fl.get("records", 0)
                        extra["flight_last_seq"] = fl.get("last_seq", 0)
                    # health verdict (ISSUE 14): classified HERE, shipped
                    # on the same beat — the gateway folds it into the
                    # engines merge and the router ejects on `stalled`
                    health, reason = watchdog.assess(stats)
                    extra["health"] = health
                    extra["health_reason"] = reason
                    extra["health_since_s"] = round(watchdog.in_state_s, 3)
                    # post-mortem triggers: a watchdog trip (once per
                    # incident) or the serve loop's own death (the crash
                    # handler left engine.last_postmortem behind). The
                    # record is held until the gateway ACCEPTS it — a
                    # gateway blip must not eat the black box.
                    if pending_pm is None:
                        pm_reason = pm_exc = ""
                        if stats.get("engine_dead") and not crash_shipped:
                            crash_shipped = True
                            pm_reason, pm_exc = ("engine_dead",
                                                 "serve loop dead")
                            # the dead engine trips the watchdog's stall
                            # flag too — SAME incident: consume it, or
                            # the next beat ships a duplicate
                            # watchdog_stall record for this death
                            watchdog.pop_stall_trip()
                        elif watchdog.pop_stall_trip():
                            pm_reason, pm_exc = "watchdog_stall", reason
                        if pm_reason:
                            # blackbox() reads live engine state next to
                            # a dead/wedged loop — a failing snapshot
                            # must degrade to a header-only record, never
                            # kill THIS loop (the replica would fall
                            # silent, the outcome the watchdog prevents)
                            try:
                                raw = (engine.last_postmortem
                                       if pm_reason == "engine_dead"
                                       and engine.last_postmortem
                                       else engine.blackbox(pm_reason,
                                                            pm_exc))
                                pending_pm = build_postmortem(
                                    container_id=cfg.container_id, **raw)
                            except Exception:   # noqa: BLE001
                                log.exception(
                                    "post-mortem snapshot failed")
                                pending_pm = build_postmortem(
                                    reason=pm_reason,
                                    exception=f"{pm_exc} (snapshot "
                                              "failed; header only)",
                                    container_id=cfg.container_id,
                                    stats={k: v for k, v in stats.items()
                                           if isinstance(v, (int, float,
                                                             str, bool))})
                    # engine spans ride the heartbeat the way worker rings
                    # ride the keepalive (worker.py ship analogue)
                    spans, ship_hi = tracer.export_new(
                        since_mono=last_span_ship, limit=RING_CAP)
                    decs, dec_hi = decision_ledger.export_new(
                        since_seq=last_dec_ship, limit=512)
                    if faults is not None and faults.active(
                            "heartbeat_loss"):
                        # induced heartbeat loss: the replica falls
                        # SILENT (stale-aging + health plane must catch
                        # it) without touching the serve loop; the span
                        # watermark does not advance, so spans re-ship
                        # once the window clears
                        await event_wait(state["beat"], timeout=beat_s)
                        state["beat"].clear()
                        continue
                    async with session.post(
                            gateway_url + "/rpc/llm/pressure",
                            json={"container_id": cfg.container_id,
                                  "token_pressure": stats["token_pressure"],
                                  "active_streams": stats["active_streams"],
                                  "extra": extra, "spans": spans,
                                  "decisions": decs},
                            timeout=aiohttp.ClientTimeout(total=5)) as resp:
                        if resp.status >= 400 and not rejected_logged:
                            rejected_logged = True
                            log.warning(
                                "pressure heartbeat rejected (%d): %s — "
                                "router/autoscaler will see no engine load",
                                resp.status, (await resp.text())[:200])
                        elif resp.status < 400:
                            rejected_logged = False
                            last_span_ship = ship_hi
                            last_dec_ship = dec_hi
                            last_tier_delta = tier_hi
                    # black-box ship AFTER the heartbeat, in its own
                    # error scope: the heartbeat is what keeps this
                    # replica visible to the fleet — a persistently
                    # failing postmortem endpoint must never starve it
                    # (3 missed beats and a HEALTHY replica reads as
                    # silent, ejected by the very plane observing it).
                    # Bounded retry on EVERY path: transient errors get
                    # 30 beats, a gateway that actively REJECTS the
                    # record (4xx — container state expired) gets 5, then
                    # the record is dropped so the trigger checks above
                    # can capture the next incident's evidence.
                    if pending_pm is not None:
                        pm_retry.next_delay()     # count the attempt;
                        # the heartbeat cadence IS the pacing
                        pm_status = 0
                        try:
                            async with session.post(
                                    gateway_url + "/rpc/llm/postmortem",
                                    json={"container_id": cfg.container_id,
                                          "record": pending_pm},
                                    timeout=aiohttp.ClientTimeout(
                                        total=5)) as resp:
                                pm_status = resp.status
                                if resp.status < 400:
                                    log.warning(
                                        "shipped post-mortem record (%s)",
                                        pending_pm.get("reason"))
                                    pending_pm = None
                                    pm_retry.reset()
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError) as exc:
                            log.debug("post-mortem ship failed: %s", exc)
                        if pending_pm is not None and pm_retry.give_up(
                                permanent=400 <= pm_status < 500):
                            log.error(
                                "dropping post-mortem record (%s) after "
                                "%d attempts (last status %d)",
                                pending_pm.get("reason"),
                                pm_retry.attempts, pm_status)
                            pending_pm = None
                            pm_retry.reset()
                except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                    log.debug("pressure heartbeat failed: %s", exc)
                # request completions nudge the next beat immediately: an
                # aggressive scale-to-zero otherwise kills the replica
                # before the beat tick and its engine spans die with it
                await event_wait(state["beat"], timeout=beat_s)
                state["beat"].clear()

    await pressure_loop() if gateway_url else await asyncio.Event().wait()


def main() -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    cfg = RunnerConfig.from_env()
    if not cfg.handler:
        print("TPU9_HANDLER not set", file=sys.stderr)
        sys.exit(2)
    asyncio.run(amain())


if __name__ == "__main__":
    main()
