"""Task-queue runner: long-polls the gateway for tasks and executes them.

Reference analogue: ``sdk/src/beta9/runner/taskqueue.py:166,298`` —
multiprocess pollers with a watchdog. tpu9 runs ``TPU9_WORKERS`` concurrent
poller coroutines in one process (handler calls execute in threads), plus the
same /health server the worker's readiness probe expects.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import time

import aiohttp
from aiohttp import web

from .common import FunctionHandler, RunnerConfig, error_payload, jsonable

log = logging.getLogger("tpu9.runner")


class TaskQueueWorker:
    def __init__(self, cfg: RunnerConfig):
        self.cfg = cfg
        self.handler = FunctionHandler(cfg)
        from ..config import env_gateway_url, env_token
        self.gateway_url = env_gateway_url()
        self.token = env_token()
        self.ready = False
        self.processed = 0
        self._session: aiohttp.ClientSession | None = None

    async def _api(self, method: str, path: str, body: dict) -> dict:
        assert self._session is not None
        async with self._session.request(
                method, self.gateway_url + path, json=body,
                timeout=aiohttp.ClientTimeout(total=60)) as resp:
            return await resp.json()

    async def poll_loop(self, idx: int) -> None:
        while True:
            t0 = time.monotonic()
            try:
                out = await self._api("POST", "/rpc/taskqueue/pop", {
                    "stub_id": self.cfg.stub_id,
                    "container_id": self.cfg.container_id,
                    "timeout": 25.0})
                task = out.get("task") if isinstance(out, dict) else None
            except asyncio.CancelledError:
                raise
            except Exception as exc:    # noqa: BLE001 — a malformed
                # gateway response (bad JSON, null body, missing keys)
                # must not crash EVERY poller and kill the container
                log.warning("pop failed: %s", exc)
                await asyncio.sleep(1.0)
                continue
            if not task:
                # a HEALTHY empty answer is a 25s long-poll timeout; an
                # INSTANT one (paused stub, error JSON) would hot-spin
                # TPU9_WORKERS pollers against the gateway
                if time.monotonic() - t0 < 1.0:
                    await asyncio.sleep(1.0)
                continue
            try:
                await self.run_task(task)
            except asyncio.CancelledError:
                raise
            except Exception as exc:    # noqa: BLE001 — run_task guards
                # user code, but a task dict missing task_id lands here
                log.warning("task run failed pre-handler: %s", exc)

    async def run_task(self, task: dict) -> None:
        task_id = task["task_id"]
        try:
            result = await asyncio.wait_for(
                self.handler.call(*task.get("args", []),
                                  **task.get("kwargs", {})),
                timeout=self.cfg.timeout_s)
            body = {"result": jsonable(result)}
        except Exception as exc:  # noqa: BLE001 — user code boundary
            body = {"error": error_payload(exc)["error"]}
        body["container_id"] = self.cfg.container_id
        self.processed += 1
        try:
            await self._api("POST", f"/rpc/task/{task_id}/complete", body)
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            log.error("failed to report completion for %s: %s", task_id, exc)

    async def main(self) -> None:
        self._session = aiohttp.ClientSession(
            headers={"Authorization": f"Bearer {self.token}"})
        # health server first so the worker's readiness probe can pass once
        # the handler is loaded
        app = web.Application()

        async def health(request: web.Request) -> web.Response:
            if not self.ready:
                return web.json_response({"ready": False}, status=503)
            return web.json_response({"ready": True,
                                      "processed": self.processed})

        app.router.add_get("/health", health)
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", self.cfg.port).start()

        await asyncio.to_thread(self.handler.load)
        self.ready = True
        log.info("taskqueue runner ready (%d pollers)", self.cfg.workers)
        await asyncio.gather(*[self.poll_loop(i)
                               for i in range(max(self.cfg.workers, 1))])


def main() -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    cfg = RunnerConfig.from_env()
    if not cfg.handler:
        print("TPU9_HANDLER not set", file=sys.stderr)
        sys.exit(2)
    asyncio.run(TaskQueueWorker(cfg).main())


if __name__ == "__main__":
    main()
