"""Runner plumbing shared by endpoint/taskqueue/function containers.

Reference analogue: ``sdk/src/beta9/runner/common.py`` — FunctionHandler
(loads the user handler from the synced workspace), lifecycle hooks
(on_start), config from env. The worker injects TPU9_* env
(lifecycle.py:_spec_from_request); this module is the consumer.
"""

from __future__ import annotations

import asyncio
import importlib
import inspect
import json
import os
import sys
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class RunnerConfig:
    container_id: str = ""
    stub_id: str = ""
    workspace_id: str = ""
    stub_type: str = "endpoint"
    handler: str = ""              # "module:function"
    port: int = 8000
    workdir: str = ""
    concurrent_requests: int = 1
    workers: int = 1
    timeout_s: float = 180.0
    inputs: dict = field(default_factory=dict)    # schema spec (tpu9.schema)
    outputs: dict = field(default_factory=dict)
    extra: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "RunnerConfig":
        e = env if env is not None else os.environ

        def spec(key: str) -> dict:
            raw = e.get(key, "")
            if not raw:
                return {}
            try:
                return json.loads(raw)
            except json.JSONDecodeError as err:
                # fail the container, loudly: silently serving with the
                # deployer's declared validation OFF would be a security
                # downgrade no one can see
                raise ValueError(f"corrupt {key} schema spec: {err}") from err

        return cls(
            container_id=e.get("TPU9_CONTAINER_ID", ""),
            stub_id=e.get("TPU9_STUB_ID", ""),
            workspace_id=e.get("TPU9_WORKSPACE_ID", ""),
            stub_type=e.get("TPU9_STUB_TYPE", "endpoint"),
            handler=e.get("TPU9_HANDLER", ""),
            port=int(e.get("TPU9_PORT", "8000")),
            workdir=e.get("TPU9_WORKDIR", os.getcwd()),
            concurrent_requests=int(e.get("TPU9_CONCURRENT_REQUESTS", "1")),
            workers=int(e.get("TPU9_WORKERS", "1")),
            timeout_s=float(e.get("TPU9_TIMEOUT_S", "180")),
            inputs=spec("TPU9_INPUTS"),
            outputs=spec("TPU9_OUTPUTS"),
        )


class FunctionHandler:
    """Loads and invokes the user handler with on_start lifecycle support."""

    def __init__(self, cfg: RunnerConfig):
        self.cfg = cfg
        self.fn: Optional[Callable] = None
        self.context: Any = None
        self.in_schema = None
        self.out_schema = None

    def load(self) -> Callable:
        if self.fn is not None:
            return self.fn
        if self.cfg.inputs or self.cfg.outputs:
            from ..schema import Schema
            if self.cfg.inputs:
                self.in_schema = Schema.from_spec(self.cfg.inputs)
            if self.cfg.outputs:
                self.out_schema = Schema.from_spec(self.cfg.outputs)
        if self.cfg.workdir and self.cfg.workdir not in sys.path:
            sys.path.insert(0, self.cfg.workdir)
        module_name, _, attr = self.cfg.handler.partition(":")
        if not module_name or not attr:
            raise ValueError(f"bad handler spec {self.cfg.handler!r}")
        module = importlib.import_module(module_name)
        target = getattr(module, attr)
        # unwrap SDK decorator objects to the raw callable
        fn = getattr(target, "func", None) or getattr(target, "__wrapped__",
                                                      None) or target
        if not callable(fn):
            raise TypeError(f"handler {self.cfg.handler!r} is not callable")
        on_start = getattr(target, "on_start", None)
        if callable(on_start):
            self.context = on_start()
        self.fn = fn
        return fn

    async def call(self, *args: Any, **kwargs: Any) -> Any:
        fn = self.load()
        sig_kwargs = dict(kwargs)
        if self.in_schema is not None and not args:
            # schema-validated stubs take kwargs-only payloads; coercion
            # happens here (base64→bytes, nested objects) before user code
            sig_kwargs = self.in_schema.validate(sig_kwargs)
        if self.context is not None:
            try:
                if "context" in inspect.signature(fn).parameters:
                    sig_kwargs["context"] = self.context
            except (TypeError, ValueError):
                pass
        if inspect.iscoroutinefunction(fn):
            result = await fn(*args, **sig_kwargs)
        else:
            result = await asyncio.to_thread(fn, *args, **sig_kwargs)
        if self.out_schema is not None and isinstance(result, dict):
            result = self.out_schema.encode_output(result)
        return result


def error_payload(exc: BaseException) -> dict:
    return {"error": f"{type(exc).__name__}: {exc}",
            "traceback": traceback.format_exc(limit=20)}


def json_default(obj: Any) -> Any:
    """Serialize common scientific types transparently."""
    try:
        import numpy as np
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
    except ImportError:
        pass
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj)}")


def dumps(obj: Any) -> str:
    return json.dumps(obj, default=json_default)


def jsonable(obj: Any) -> Any:
    """Coerce a handler result into a JSON-safe value (numpy arrays etc.);
    falls back to repr rather than crashing the runner."""
    try:
        json.dumps(obj)
        return obj
    except TypeError:
        try:
            return json.loads(dumps(obj))
        except TypeError:
            return repr(obj)
