"""Runner-side checkpoint hooks: save/restore model state inside the
container workdir so the worker's filesystem snapshot carries it.

Reference analogue: the SDK runner's ``wait_for_checkpoint`` cooperation
(``sdk/src/beta9/runner/common.py``) — here inverted for TPUs: instead of
CRIU freezing the process, the runner persists the expensive-to-rebuild state
(model params via orbax, plus anything the handler adds) and marks readiness;
a restored container finds the state and skips re-initialization.

Handler usage:

    from tpu9.runner import ckpt

    def load_model():
        params = ckpt.maybe_restore(lambda: init_decoder(rng, cfg))
        ...
        ckpt.mark_ready()          # worker snapshots after this
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable

log = logging.getLogger("tpu9.runner")

CKPT_DIR_NAME = ".tpu9-ckpt"


def ckpt_dir() -> str:
    base = os.environ.get("TPU9_WORKDIR", os.getcwd())
    d = os.path.join(base, CKPT_DIR_NAME)
    os.makedirs(d, exist_ok=True)
    return d


def is_restored() -> bool:
    return os.path.exists(os.path.join(ckpt_dir(), "READY"))


def mark_ready(meta: dict | None = None) -> None:
    with open(os.path.join(ckpt_dir(), "meta.json"), "w") as f:
        json.dump(meta or {}, f)
    with open(os.path.join(ckpt_dir(), "READY"), "w") as f:
        f.write("1")


def save_params(params: Any, name: str = "params") -> str:
    """Persist a jax pytree with orbax (async-barrier'd, overwrite-safe)."""
    import orbax.checkpoint as ocp
    path = os.path.join(ckpt_dir(), name)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, params, force=True)
    return path


def load_params(name: str = "params", template: Any = None) -> Any:
    import orbax.checkpoint as ocp
    path = os.path.join(ckpt_dir(), name)
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        return ckptr.restore(path, item=template)
    return ckptr.restore(path)


def maybe_restore(init_fn: Callable[[], Any], name: str = "params") -> Any:
    """Restore saved params when running from a checkpoint; otherwise init
    and save them so the next cold start restores."""
    path = os.path.join(ckpt_dir(), name)
    if is_restored() and os.path.exists(path):
        log.info("restoring %s from checkpoint", name)
        return load_params(name)
    params = init_fn()
    if os.environ.get("TPU9_CHECKPOINT_ENABLED") == "1":
        log.info("saving %s for future restores", name)
        save_params(params, name)
    return params
