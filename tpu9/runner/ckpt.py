"""Runner-side checkpoint hooks: save/restore model state inside the
container workdir so the worker's filesystem snapshot carries it.

Reference analogue: the SDK runner's ``wait_for_checkpoint`` cooperation
(``sdk/src/beta9/runner/common.py``) — here inverted for TPUs: instead of
CRIU freezing the process, the runner persists the expensive-to-rebuild state
(model params as streamable ``.tpu9w`` shards — tpu9.serving.weights — plus
anything the handler adds) and marks readiness; a restored container finds
the state and skips re-initialization, and the worker's streaming restore +
warm weights pool recognize the shard dirs by suffix.

Handler usage:

    from tpu9.runner import ckpt

    def load_model():
        params = ckpt.maybe_restore(lambda: init_decoder(rng, cfg))
        ...
        ckpt.mark_ready()          # worker snapshots after this
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable

log = logging.getLogger("tpu9.runner")

CKPT_DIR_NAME = ".tpu9-ckpt"


def ckpt_dir() -> str:
    base = os.environ.get("TPU9_WORKDIR", os.getcwd())
    d = os.path.join(base, CKPT_DIR_NAME)
    os.makedirs(d, exist_ok=True)
    return d


def is_restored() -> bool:
    return os.path.exists(os.path.join(ckpt_dir(), "READY"))


def mark_ready(meta: dict | None = None) -> None:
    with open(os.path.join(ckpt_dir(), "meta.json"), "w") as f:
        json.dump(meta or {}, f)
    with open(os.path.join(ckpt_dir(), "READY"), "w") as f:
        f.write("1")


def _weights_path(name: str) -> str:
    from ..serving import weights as wfmt
    return os.path.join(ckpt_dir(), name + wfmt.WEIGHTS_SUFFIX)


def save_params(params: Any, name: str = "params",
                quantize: str | None = None) -> str:
    """Persist a jax pytree in the streamable ``.tpu9w`` shard format
    (tpu9.serving.weights) — raw per-leaf shards the worker's restore can
    feed straight from cache chunks into host buffers / the warm weights
    pool, with no container framing to parse.

    ``quantize`` (default: the ``TPU9_CKPT_QUANT`` env, e.g. ``"int8"``)
    quantizes decoder projections at SAVE time, emitting ~2x-smaller v2
    shards — every restore downstream (chunk fetch, peer reads, warm
    pool, device puts) then moves half the bytes for free. Opt-in per
    deployment: the saved tree is what a restore serves, so only set it
    for presets meant to serve int8.

    Trees the format cannot represent — multi-host sharded ``jax.Array``s
    (``np.asarray`` raises on non-addressable shards), NamedTuple
    containers, custom pytree nodes — fall back to the legacy orbax
    directory, which ``load_params`` still restores."""
    from ..serving import weights as wfmt
    if quantize is None:
        quantize = os.environ.get("TPU9_CKPT_QUANT", "") or None
    if quantize:
        # quantize BEFORE the representability try/except below: a bad
        # mode (operator typo) or a quantizer bug must fail LOUDLY here,
        # not ride the orbax fallback and silently ship full-size
        # unquantized shards the operator sized HBM/restore around
        from ..ops.quant import validate_quant_mode
        validate_quant_mode(quantize)
        if quantize != "int8":
            # validated-but-unwired (a future SUPPORTED_MODES entry) must
            # fail, not silently emit int8 shards for an fp8 opt-in
            raise NotImplementedError(
                f"quantize mode {quantize!r} is not wired into ckpt save")
        if isinstance(params, dict) and "layers" in params:
            from ..ops.quant import quantize_decoder
            params = quantize_decoder(params)   # idempotent on int8 trees
        else:
            # the env var is deployment-wide; a handler's NON-decoder
            # side state (optimizer stats, tokenizer tables) must still
            # save streamable, just unquantized
            log.info("params %r is not a decoder tree; saving "
                     "unquantized despite TPU9_CKPT_QUANT=%s", name,
                     quantize)
    path = _weights_path(name)
    try:
        # the format's flatten np.asarray's each leaf — device arrays are
        # pulled to host there, python scalars ride in the index skeleton
        wfmt.save_params(params, path)
        return path
    except Exception as exc:       # noqa: BLE001 — any non-representable
        import shutil              # tree degrades to the orbax path
        shutil.rmtree(path, ignore_errors=True)   # a partial .tpu9w dir
        log.info("params %r not streamable (%s); saving via orbax", name,
                 exc)                             # would shadow the orbax
    import orbax.checkpoint as ocp                # dir on load
    legacy = os.path.join(ckpt_dir(), name)
    ocp.PyTreeCheckpointer().save(legacy, params, force=True)
    return legacy


def load_params(name: str = "params", template: Any = None,
                mmap: bool = False) -> Any:
    """Load saved params: ``.tpu9w`` shard dirs first (``mmap=True`` maps
    shards lazily instead of reading them up front), falling back to a
    legacy orbax directory from pre-streaming checkpoints. ``template``
    only shapes LEGACY orbax restores — a ``.tpu9w`` dir reproduces the
    saved tree structure exactly (tuples included) and ignores it."""
    path = _weights_path(name)
    if os.path.isdir(path):
        from ..observability import coldstart as _cs
        from ..observability.trace import tracer
        from ..serving import weights as wfmt
        # restore.load (ISSUE 13): the runner-side host load of the
        # worker-spilled shards — inherits the runner.bringup parent via
        # the contextvar, so the bring-up trace stays gapless
        with tracer.span(_cs.SPAN_LOAD,
                         attrs={"name": name, "source": "tpu9w",
                                "mmap": mmap}):
            return wfmt.load_params(path, mmap=mmap)
    import orbax.checkpoint as ocp
    legacy = os.path.join(ckpt_dir(), name)
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        return ckptr.restore(legacy, item=template)
    return ckptr.restore(legacy)


def maybe_restore(init_fn: Callable[[], Any], name: str = "params") -> Any:
    """Restore saved params when running from a checkpoint; otherwise init
    and save them so the next cold start restores."""
    if is_restored() and (os.path.isdir(_weights_path(name))
                          or os.path.exists(os.path.join(ckpt_dir(), name))):
        log.info("restoring %s from checkpoint", name)
        return load_params(name)
    params = init_fn()
    from ..config import env_checkpoint_enabled
    if env_checkpoint_enabled():
        log.info("saving %s for future restores", name)
        save_params(params, name)
    return params
