"""Minimal ASGI-over-aiohttp adapter for @asgi stubs.

Reference analogue: the reference hosts user ASGI apps under
gunicorn+uvicorn (``sdk/src/beta9/runner/endpoint.py:70-90``). Neither is in
the tpu9 runner image, so this adapter translates aiohttp requests into ASGI
http scope events for the user's app (FastAPI/Starlette/raw ASGI).

Scope: the http protocol with buffered request/response bodies. Incremental
streaming (SSE/chunked) and websocket ASGI apps are not yet supported —
responses are delivered when the app completes (see ROADMAP.md); @realtime
covers the websocket use case.
"""

from __future__ import annotations

from typing import Any

from aiohttp import web


async def run_asgi_http(app: Any, request: web.Request) -> web.Response:
    """Drive one request through an ASGI app; returns the aiohttp response."""
    body = await request.read()
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": request.path,
        "raw_path": request.raw_path.encode(),
        "query_string": request.query_string.encode(),
        "root_path": "",
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in request.headers.items()],
        "client": (request.remote or "127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }

    received = {"sent": False}
    import asyncio

    async def receive() -> dict:
        if received["sent"]:
            # ASGI: http.disconnect only when the client actually goes away;
            # apps (e.g. Starlette's listen_for_disconnect) block here —
            # returning disconnect early would cancel streaming responses
            await asyncio.Event().wait()
        received["sent"] = True
        return {"type": "http.request", "body": body, "more_body": False}

    state: dict = {"status": 500, "headers": [], "chunks": []}

    async def send(message: dict) -> None:
        if message["type"] == "http.response.start":
            state["status"] = message["status"]
            state["headers"] = message.get("headers", [])
        elif message["type"] == "http.response.body":
            chunk = message.get("body", b"")
            if chunk:
                state["chunks"].append(chunk)

    await app(scope, receive, send)

    # multidict: duplicate headers (multiple Set-Cookie) must survive
    from multidict import CIMultiDict
    headers: CIMultiDict = CIMultiDict()
    for k, v in state["headers"]:
        name = k.decode() if isinstance(k, bytes) else k
        value = v.decode() if isinstance(v, bytes) else v
        if name.lower() == "content-length":
            continue
        headers.add(name, value)
    return web.Response(status=state["status"], body=b"".join(state["chunks"]),
                        headers=headers)


def looks_like_asgi(obj: Any) -> bool:
    """ASGI apps are callables taking (scope, receive, send)."""
    import inspect
    if not callable(obj):
        return False
    try:
        target = obj if inspect.isfunction(obj) or inspect.ismethod(obj) \
            else obj.__call__
        params = inspect.signature(target).parameters
        names = [p for p in params
                 if params[p].kind in (params[p].POSITIONAL_ONLY,
                                       params[p].POSITIONAL_OR_KEYWORD)]
        return len(names) >= 3
    except (ValueError, TypeError):
        return False
