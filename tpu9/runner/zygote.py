"""Pre-warmed runner template (fork-server) — sub-second JAX cold starts.

Reference analogue: the reference kills runner cold-start cost with CRIU —
it auto-checkpoints a container right after readiness and restores that
image for every later start (``/root/reference/pkg/worker/criu.go:392``).
tpu9's TPU-first equivalent for the *process* runtime is a zygote: one
long-lived process per worker that has already paid the expensive imports
(jax, numpy, aiohttp, the tpu9 runner modules) **without initializing any
accelerator backend**, and forks a child per container. The child applies
the container's env/cwd/stdio, re-points JAX's config at the env it just
received (the zygote's import-time config must not leak in), and runs the
runner module — skipping interpreter boot + imports entirely.

Fork-safety contract (verified by tests/test_zygote.py):
- the zygote imports but NEVER runs a jax computation → no backend client,
  no XLA thread pools; after warmup only MainThread exists
- children initialize their own backend post-fork (CPU or the TPU tunnel,
  per their env), so device state is never shared across forks

Protocol (SOCK_STREAM unix socket, one connection per spawn):
  worker → zygote: JSON line {"env": {...}, "cwd": ..., "module": ...,
                    "argv": [...]} with [stdout_w, stderr_w] fds attached
                    via SCM_RIGHTS on the first byte
  zygote → worker: {"pid": N}\n  …then, when the child exits…
                   {"exit": code}\n  (connection close = zygote died)
"""

from __future__ import annotations

import array
import json
import os
import selectors
import signal
import socket
import sys

PRELOADS = ("jax", "jax.numpy", "numpy", "aiohttp",
            "tpu9.runner.common", "tpu9.runner.endpoint",
            "tpu9.runner.taskqueue", "tpu9.runner.function")


def _warm_imports() -> None:
    import importlib
    # neutralize any ambient platform pin for the ZYGOTE process only: the
    # import must not dial an accelerator; children re-pin from their env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    for mod in PRELOADS:
        try:
            importlib.import_module(mod)
        except Exception as exc:      # noqa: BLE001 — degraded, not fatal
            print(f"zygote: preload {mod} failed: {exc}", file=sys.stderr)


def _child_setup(req: dict, stdout_fd: int, stderr_fd: int) -> None:
    # undo the zygote's own signal handling: a runner child must die on
    # SIGTERM exactly like an exec'd runner would (the worker's stop path
    # sends SIGTERM and only escalates after a grace period)
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        signal.signal(sig, signal.SIG_DFL)
    os.setsid()
    os.dup2(stdout_fd, 1)
    os.dup2(stderr_fd, 2)
    os.close(stdout_fd)
    os.close(stderr_fd)
    env = req.get("env", {})
    os.environ.clear()
    os.environ.update(env)
    cwd = req.get("cwd") or "/"
    os.chdir(cwd)
    # the interpreter is already up: PYTHONPATH in env is NOT re-read, so
    # mirror it into sys.path (front, preserving order) for app imports
    for entry in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
        if entry and entry not in sys.path:
            sys.path.insert(0, entry)
    if cwd not in sys.path:
        sys.path.insert(0, cwd)
    # re-point JAX at THIS container's platform/cache config — the values
    # were frozen from the zygote's env at import time
    try:
        import jax
        for env_key, cfg_key, conv in (
                ("JAX_PLATFORMS", "jax_platforms", str),
                ("JAX_COMPILATION_CACHE_DIR",
                 "jax_compilation_cache_dir", str),
                ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                 "jax_persistent_cache_min_compile_time_secs", float),
                ("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES",
                 "jax_persistent_cache_min_entry_size_bytes", int)):
            if env_key in env:
                try:
                    jax.config.update(cfg_key, conv(env[env_key]))
                except (ValueError, AttributeError):
                    pass
    except Exception:                 # noqa: BLE001
        pass
    sys.argv = [req.get("module", "")] + list(req.get("argv", []))


def _spawn(conn: socket.socket, req: dict, fds: list[int],
           inherited: list[socket.socket]) -> int:
    pid = os.fork()
    if pid != 0:
        for fd in fds:
            os.close(fd)
        return pid
    # ---- child ----
    try:
        # drop EVERY inherited zygote fd: the listener and other children's
        # notify connections. A long-lived child holding a sibling's conn
        # open would keep the worker's exit-watch readline from ever seeing
        # EOF after a zygote crash — containers would look immortal.
        conn.close()
        for s in inherited:
            try:
                s.close()
            except OSError:
                pass
        _child_setup(req, fds[0], fds[1])
        module = req["module"]
        import importlib
        mod = importlib.import_module(module) \
            if module in sys.modules or module in PRELOADS else None
        if mod is not None and hasattr(mod, "main"):
            # preloaded runner: call its entrypoint directly (runpy would
            # warn about re-executing an already-imported module)
            mod.main()
        else:
            import runpy
            runpy.run_module(module, run_name="__main__", alter_sys=True)
        code = 0
    except SystemExit as exc:
        code = exc.code if isinstance(exc.code, int) else 0
    except BaseException:             # noqa: BLE001
        import traceback
        traceback.print_exc()
        code = 1
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def _recv_request(conn: socket.socket):
    """First datagram carries the fds; read until newline for the JSON."""
    buf = bytearray()
    fds: list[int] = []
    while b"\n" not in buf:
        if not fds:
            msg, anc, _flags, _addr = conn.recvmsg(
                65536, socket.CMSG_LEN(2 * array.array("i").itemsize))
            for level, typ, data in anc:
                if level == socket.SOL_SOCKET and typ == socket.SCM_RIGHTS:
                    a = array.array("i")
                    a.frombytes(data[:len(data) - len(data) % a.itemsize])
                    fds.extend(a)
        else:
            msg = conn.recv(65536)
        if not msg:
            return None, fds
        buf.extend(msg)
    line = bytes(buf).split(b"\n", 1)[0]
    return json.loads(line), fds


def serve(sock_path: str) -> None:
    _warm_imports()
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(64)
    srv.setblocking(False)
    print("zygote: ready", flush=True)

    sel = selectors.DefaultSelector()
    sel.register(srv, selectors.EVENT_READ, "accept")
    children: dict[int, socket.socket] = {}    # pid -> notify conn

    def close_conn(conn: socket.socket) -> None:
        try:
            sel.unregister(conn)
        except (KeyError, ValueError):
            pass
        try:
            conn.close()
        except OSError:
            pass

    def reap() -> None:
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            conn = children.pop(pid, None)
            if conn is not None:
                code = (os.WEXITSTATUS(status) if os.WIFEXITED(status)
                        else 128 + os.WTERMSIG(status))
                try:
                    conn.sendall(json.dumps({"exit": code}).encode() + b"\n")
                except OSError:
                    pass
                close_conn(conn)

    while True:
        events = sel.select(timeout=0.2)
        reap()
        for key, _mask in events:
            if key.data == "accept":
                try:
                    conn, _ = srv.accept()
                except OSError:
                    continue
                # bounded handshake: a half-open client must not wedge the
                # single-threaded fork-server (every later spawn would
                # stall into its exec fallback, then fork a duplicate
                # whenever the zygote unwedged)
                conn.settimeout(10.0)
                try:
                    req, fds = _recv_request(conn)
                    conn.settimeout(None)
                except (OSError, ValueError):
                    conn.close()
                    continue
                if req is None or len(fds) < 2:
                    for fd in fds:
                        os.close(fd)
                    conn.close()
                    continue
                pid = _spawn(conn, req, fds,
                             [srv] + list(children.values()))
                children[pid] = conn
                # watch the worker's end: the protocol has no further
                # client→zygote traffic, so the only READ event on this
                # conn is EOF — the worker died or abandoned the spawn
                # (e.g. its pid-reply read timed out). Its child must not
                # keep running unsupervised while the worker falls back to
                # exec and forks a duplicate (advisor r04).
                sel.register(conn, selectors.EVENT_READ, ("client", pid))
                try:
                    conn.sendall(json.dumps({"pid": pid}).encode() + b"\n")
                except OSError:
                    pass
            else:
                _kind, pid = key.data
                conn = key.fileobj
                try:
                    data = conn.recv(4096)
                except OSError:
                    data = b""
                if data:
                    continue               # stray bytes: ignore, stay open
                if children.pop(pid, None) is not None:
                    # the child setsid()s at startup (pgid == pid) and
                    # runner workloads fork their own subprocesses — kill
                    # the whole group, or the grandchildren survive as the
                    # very duplicates this path exists to prevent
                    try:
                        os.killpg(pid, signal.SIGKILL)
                    except ProcessLookupError:
                        # pre-setsid race: fall back to the lone pid
                        try:
                            os.kill(pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
                close_conn(conn)


def main() -> None:
    sock_path = sys.argv[sys.argv.index("--sock") + 1] \
        if "--sock" in sys.argv else os.environ.get("TPU9_ZYGOTE_SOCK", "")
    if not sock_path:
        print("usage: python -m tpu9.runner.zygote --sock PATH",
              file=sys.stderr)
        sys.exit(2)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    serve(sock_path)


if __name__ == "__main__":
    main()
