from .scheduler import Scheduler
from .selector import filter_workers, score_worker, select_worker
from .pools import LocalProcessPool, WorkerPoolController

__all__ = ["Scheduler", "filter_workers", "score_worker", "select_worker",
           "LocalProcessPool", "WorkerPoolController"]
