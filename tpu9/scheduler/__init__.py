from .scheduler import Scheduler
from .selector import filter_workers, score_worker, select_worker
from .pools import (AgentMachinePool, GceTpuPool, LocalProcessPool,
                    WorkerPoolController)

__all__ = ["Scheduler", "filter_workers", "score_worker", "select_worker",
           "AgentMachinePool", "GceTpuPool", "LocalProcessPool",
           "WorkerPoolController"]
