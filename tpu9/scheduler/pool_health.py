"""Pool sizing, health monitoring, and stale-worker cleanup.

Reference analogue: ``pkg/scheduler/pool_sizing.go`` (keep min free
CPU/GPU/mem warm), ``pool_health.go:41-305`` (pool status from worker/
container state), ``pool_cleaner.go:28-207`` (prune stale workers). The TPU
twist: sizing counts free chips per slice shape, and pruning a dead gang
member marks its whole gang lost (shared fate) so peers are reaped too.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Optional

from ..config import WorkerPoolConfig
from ..observability import metrics
from ..repository import ContainerRepository, WorkerRepository
from ..statestore import StateStore
from ..types import ContainerRequest, StopReason, WorkerStatus
from ..utils.aio import reap

log = logging.getLogger("tpu9.scheduler")


@dataclass
class PoolStatus:
    name: str
    healthy: bool
    workers: int
    alive: int
    free_cpu_millicores: int = 0
    free_memory_mb: int = 0
    free_chips: int = 0
    reason: str = ""


class PoolMonitor:
    """One loop per cluster: sizes warm pools, degrades unhealthy ones, and
    reaps workers whose keepalive lapsed."""

    def __init__(self, store: StateStore,
                 pools: dict[str, "WorkerPoolController"],
                 pool_cfgs: dict[str, WorkerPoolConfig],
                 interval_s: float = 5.0, quota=None):
        self.workers = WorkerRepository(store)
        self.containers = ContainerRepository(store)
        self.store = store
        self.pools = pools
        self.pool_cfgs = pool_cfgs
        self.interval_s = interval_s
        self.quota = quota            # Optional[QuotaService]
        self._last_quota_reconcile = 0.0
        self.status: dict[str, PoolStatus] = {}
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "PoolMonitor":
        if self._task is None:
            self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            # reap: swallows the child's CancelledError but re-raises if
            # stop() itself is cancelled mid-drain (ASY003)
            await reap(self._task)
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("pool monitor tick failed")
            await asyncio.sleep(self.interval_s)

    async def tick(self) -> None:
        # orphaned quota-charge sweep, at a much slower cadence than the
        # worker-health pass (charges only orphan when a host dies hard)
        if self.quota is not None and \
                time.time() - self._last_quota_reconcile > 60.0:
            self._last_quota_reconcile = time.time()
            await self.quota.reconcile()
        all_workers = await self.workers.list()
        by_pool: dict[str, list] = {}
        for w in all_workers:
            by_pool.setdefault(w.pool, []).append(w)

        for name, cfg in self.pool_cfgs.items():
            members = by_pool.get(name, [])
            alive = []
            for w in members:
                if await self.workers.is_alive(w.worker_id):
                    alive.append(w)
                else:
                    await self._reap(w)
            status = PoolStatus(
                name=name, workers=len(members), alive=len(alive),
                free_cpu_millicores=sum(w.free_cpu_millicores for w in alive),
                free_memory_mb=sum(w.free_memory_mb for w in alive),
                free_chips=sum(w.tpu_free_chips for w in alive),
                healthy=True)
            if members and not alive:
                status.healthy = False
                status.reason = "no live workers"
            self.status[name] = status
            metrics.set_gauge("tpu9_pool_workers", len(alive),
                              {"pool": name})
            metrics.set_gauge("tpu9_pool_free_chips", status.free_chips,
                              {"pool": name})
            await self._maybe_warm(name, cfg, status)

    async def _maybe_warm(self, name: str, cfg: WorkerPoolConfig,
                          status: PoolStatus) -> None:
        """Keep-warm sizing (pool_sizing.go:45 semantics)."""
        need = ((cfg.min_free_cpu_millicores and
                 status.free_cpu_millicores < cfg.min_free_cpu_millicores)
                or (cfg.min_free_memory_mb and
                    status.free_memory_mb < cfg.min_free_memory_mb)
                or (cfg.min_free_tpu_chips and
                    status.free_chips < cfg.min_free_tpu_chips))
        if not need:
            return
        pool = self.pools.get(name)
        if pool is None:
            return
        request = ContainerRequest(tpu=cfg.tpu_type,
                                   cpu_millicores=cfg.min_free_cpu_millicores,
                                   memory_mb=cfg.min_free_memory_mb,
                                   pool_selector=name)
        if await pool.can_host(request):
            log.info("pool %s below warm threshold; adding worker", name)
            metrics.inc("tpu9_pool_warmups", labels={"pool": name})
            await pool.add_worker(request)

    async def _reap(self, worker) -> None:
        """Keepalive lapsed: fail its containers (gang peers share the fate)
        and drop the registration (pool_cleaner.go semantics)."""
        log.warning("reaping dead worker %s (pool %s)", worker.worker_id,
                    worker.pool)
        metrics.inc("tpu9_workers_reaped", labels={"pool": worker.pool})
        container_ids = await self.workers.worker_container_ids(
            worker.worker_id)
        gang_ids = set()
        for container_id in container_ids:
            state = await self.containers.get_state(container_id)
            if state is not None and state.gang_id:
                gang_ids.add(state.gang_id)
            await self._fail_container(container_id,
                                       StopReason.WORKER_LOST.value)
        # shared fate: stop every member of affected gangs
        for gang_id in gang_ids:
            raw = await self.store.hgetall(f"scheduler:gang:{gang_id}")
            import json as _json
            for peer_id in _json.loads(raw.get("containers", "[]")):
                state = await self.containers.get_state(peer_id)
                if state is not None and state.worker_id:
                    await self.store.publish(
                        f"container:stop:{state.worker_id}",
                        {"container_id": peer_id,
                         "reason": StopReason.GANG_PEER_FAILED.value})
        await self.workers.deregister(worker.worker_id)

    async def _fail_container(self, container_id: str, reason: str) -> None:
        state = await self.containers.get_state(container_id)
        if state is None:
            return
        await self.containers.set_exit_code(container_id, -1, reason)
        await self.containers.delete_state(container_id, state.stub_id)
        await self.store.publish("events:container_exit",
                                 {"container_id": container_id,
                                  "stub_id": state.stub_id})
