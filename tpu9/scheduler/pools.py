"""Worker pool controllers.

Reference analogue: ``pkg/scheduler/pool.go:52`` WorkerPoolController and its
implementations (k8s Jobs ``pool_local.go``, provider VMs
``pool_provider.go``). tpu9 ships:

- :class:`LocalProcessPool` — workers as in-process asyncio objects (dev,
  tests, the bench cold-start harness; also the single-binary deployment).
- :class:`GceTpuPool` — shapes the GCP queued-resources/TPU-VM API calls for
  provisioning v5e/v5p slices with ICI-topology awareness. Network calls are
  behind an injected transport so the control flow is testable in a
  zero-egress image; on a real deployment the transport is aiohttp → GCP.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Awaitable, Callable, Optional

from ..config import WorkerPoolConfig
from ..types import ContainerRequest, TPU_REGISTRY, new_id, parse_tpu_spec

log = logging.getLogger("tpu9.scheduler")


class WorkerPoolController:
    name = "base"

    async def can_host(self, request: ContainerRequest) -> bool:
        raise NotImplementedError

    async def add_worker(self, request: ContainerRequest) -> None:
        """Provision capacity able to host ``request`` (async; the scheduler
        retries the request until the worker registers)."""
        raise NotImplementedError

    async def worker_count(self) -> int:
        raise NotImplementedError

    async def shutdown(self) -> None:
        pass


class LocalProcessPool(WorkerPoolController):
    """Spawns Worker objects in-process on demand.

    ``worker_factory(tpu_chips)`` builds+starts a worker; the pool tracks and
    later drains them. For multi-host specs it spawns ``spec.hosts`` workers
    sharing a fresh slice_id (virtual slice — exactly how multi-host gangs are
    exercised without metal)."""

    name = "local"

    def __init__(self, cfg: WorkerPoolConfig,
                 worker_factory: Callable[..., Awaitable]):
        self.cfg = cfg
        self.worker_factory = worker_factory
        self.workers: list = []
        self._lock = asyncio.Lock()

    async def can_host(self, request: ContainerRequest) -> bool:
        if len(self.workers) >= self.cfg.max_workers:
            return False
        spec = request.tpu_spec()
        if spec is None:
            return True
        pool_spec = parse_tpu_spec(self.cfg.tpu_type) if self.cfg.tpu_type else None
        if pool_spec is None:
            return False
        return (pool_spec.generation == spec.generation
                and pool_spec.chips_per_host >= spec.chips_per_host)

    async def add_worker(self, request: ContainerRequest) -> None:
        spec = request.tpu_spec()
        async with self._lock:
            if len(self.workers) >= self.cfg.max_workers:
                return
            if spec is None or not spec.multi_host:
                chips = spec.chips_per_host if spec else 0
                w = await self.worker_factory(
                    pool=self.cfg.name, tpu_chips=chips,
                    tpu_generation=spec.generation if spec else "")
                self.workers.append(w)
                return
            # virtual multi-host slice: N workers sharing a slice id
            slice_id = new_id("slice")
            for rank in range(spec.hosts):
                w = await self.worker_factory(
                    pool=self.cfg.name, tpu_chips=spec.chips_per_host,
                    tpu_generation=spec.generation, slice_id=slice_id,
                    slice_topology=spec.topology, slice_host_rank=rank,
                    slice_host_count=spec.hosts)
                self.workers.append(w)

    async def worker_count(self) -> int:
        return len(self.workers)

    async def shutdown(self) -> None:
        for w in self.workers:
            try:
                await w.stop()
            except Exception:
                pass
        self.workers.clear()


def default_startup_script() -> str:
    """The in-repo TPU-VM bootstrap (deploy/gcp/startup-script.sh): reads
    its join parameters back out of the instance metadata this pool sets,
    then systemd-runs a native-runtime worker. Ships with the repo so a
    provisioned slice needs no other artifact (VERDICT r03 #10)."""
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "deploy", "gcp", "startup-script.sh")
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


class GceTpuPool(WorkerPoolController):
    """GCP TPU-VM slice provisioner (reference: provider VM pools,
    ``pool_provider.go:53`` + ``pkg/providers``).

    Maps a request's slice shape to a queued-resource create call:
    ``v5p-64`` → accelerator_type=v5p-128 — the API's v5p/v4 names count
    TENSORCORES (2/chip) and v5e is "v5litepod-N"; see
    ``tpu9.types.gce_accelerator_type`` (16 hosts share the slice; each host
    boots a tpu9 worker via startup script that joins this cluster with
    slice_id = the queued resource name). ``transport(method, url, body)`` is
    injected; tests assert on the calls, production passes an authed client.
    """

    name = "gce-tpu"

    def __init__(self, cfg: WorkerPoolConfig,
                 transport: Optional[Callable[..., Awaitable[dict]]] = None,
                 startup_script: str = "",
                 join_info: Optional[dict] = None):
        self.cfg = cfg
        self.transport = transport
        self.startup_script = startup_script or default_startup_script()
        # gateway join parameters the booted hosts read from metadata
        # (gateway_url / gateway_state / worker_token)
        self.join_info = join_info or {}
        self.pending: list[dict] = []

    def _base_url(self) -> str:
        from ..compute.vendors import tpu_api_base
        return tpu_api_base(self.cfg.gcp_project, self.cfg.gcp_zone)

    async def can_host(self, request: ContainerRequest) -> bool:
        spec = request.tpu_spec()
        if spec is None:
            return False
        pool_spec = parse_tpu_spec(self.cfg.tpu_type) if self.cfg.tpu_type else None
        if pool_spec and pool_spec.generation != spec.generation:
            return False
        if len(self.pending) >= self.cfg.max_workers:
            return False
        # slices take minutes to become ACTIVE — don't provision another one
        # for every scheduler retry of the same shape
        if any(p["spec"] == spec.name for p in self.pending):
            return False
        return self.transport is not None

    async def add_worker(self, request: ContainerRequest) -> None:
        spec = request.tpu_spec()
        assert spec is not None
        node_id = new_id("tpu9-node")
        body = {
            "tpu": {"node_spec": [{
                "parent": f"projects/{self.cfg.gcp_project}/locations/{self.cfg.gcp_zone}",
                "node_id": node_id,
                "node": {
                    "accelerator_type": spec.gce_accelerator_type,
                    "runtime_version": self.cfg.runtime_version,
                    "network_config": {"enable_external_ips": False},
                    "metadata": {"startup-script": self.startup_script,
                                 "tpu9-slice-id": node_id,
                                 "tpu9-slice-topology": spec.topology,
                                 "tpu9-slice-hosts": str(spec.hosts),
                                 "tpu9-tpu-gen": spec.generation,
                                 "tpu9-pool": self.cfg.name,
                                 "tpu9-gateway-url":
                                     self.join_info.get("gateway_url", ""),
                                 "tpu9-gateway-state":
                                     self.join_info.get("gateway_state", ""),
                                 "tpu9-worker-token":
                                     self.join_info.get("worker_token", "")},
                },
            }]},
            "queueing_policy": ({"valid_until_duration": "600s"}
                                if not self.cfg.reserved else {}),
        }
        if self.cfg.spot:
            body["tpu"]["node_spec"][0]["node"]["scheduling_config"] = {
                "preemptible": True}
        self.pending.append({"node_id": node_id, "spec": spec.name})
        assert self.transport is not None
        await self.transport(
            "POST", f"{self._base_url()}/queuedResources?queued_resource_id={node_id}",
            body)

    async def worker_count(self) -> int:
        return len(self.pending)

    async def reconcile(self) -> None:
        """Poll queued-resource states and drop failed/long-pending entries
        (analogue of provider Reconcile, providers/provider.go:26)."""
        if self.transport is None:
            return
        still = []
        for entry in self.pending:
            resp = await self.transport(
                "GET", f"{self._base_url()}/queuedResources/{entry['node_id']}",
                None)
            state = (resp or {}).get("state", {}).get("state", "")
            if state in ("FAILED", "SUSPENDED"):
                log.warning("queued resource %s entered %s", entry["node_id"], state)
                continue
            if state != "ACTIVE":
                still.append(entry)
        self.pending = still


class AgentMachinePool(WorkerPoolController):
    """Capacity backed by operator-owned machines running ``tpu9 agent``
    (reference ``pkg/agent`` + ``pool_agent.go``): each registered machine
    polls its desired worker-slot count and reconciles local worker
    processes against it. ``add_worker`` ranks the machines' offers with
    the marketplace ordering (price + reliability advertised at join —
    ``tpu9.compute.offer_sort_key``, reference pkg/compute/solver.go:18)
    and bumps the CHEAPEST eligible machine's desired count — the agent
    does the spawning, and the workers register through the normal path.
    Each placement is recorded as a reservation (reference
    state.go:73-109) in the statestore."""

    name = "agent"

    # reservation records live this long past placement — observability
    # only (billing reads usage metering, not reservations)
    RESERVATION_TTL_S = 24 * 3600.0

    def __init__(self, cfg: WorkerPoolConfig, backend, store):
        self.cfg = cfg
        self.backend = backend
        self.store = store

    async def _machines(self) -> list[dict]:
        from ..repository.keys import Keys
        out = []
        for m in await self.backend.list_machines(self.cfg.name):
            if m["status"] != "registered":
                continue
            hb = await self.store.get(Keys.machine_heartbeat(m["machine_id"]))
            if hb is None:
                continue                     # agent not reporting → not usable
            m["desired"] = int(await self.store.get(
                Keys.machine_desired(m["machine_id"])) or 0)
            out.append(m)
        return out

    def _demand(self, request: ContainerRequest):
        from ..compute import Demand
        spec = request.tpu_spec()
        return Demand(
            nodes=1,
            tpu_generation=spec.generation if spec is not None else "",
            tpu_chips=spec.chips_per_host if spec is not None else 0)

    def _offers(self, machines: list[dict]) -> list:
        from ..compute import Offer
        return [Offer(offer_id=m["machine_id"], provider="agent",
                      tpu_generation=m["tpu_generation"],
                      tpu_chips=m["tpu_chips"],
                      hourly_cost_micros=int(
                          m.get("hourly_cost_micros") or 0),
                      reliability=float(m.get("reliability") or 1.0),
                      available=m["max_workers"] - m["desired"])
                for m in machines]

    async def _eligible(self, request: ContainerRequest) -> list[dict]:
        """Machines with a free slot that satisfy the request's TPU shape,
        CHEAPEST FIRST (solver ranking) — the ONE eligibility+ordering
        path can_host/add_worker share."""
        from ..compute import eligible, offer_sort_key
        spec = request.tpu_spec()
        if spec is not None and spec.multi_host:
            return []             # multi-host slices need the GCE pool
        machines = await self._machines()
        by_id = {m["machine_id"]: m for m in machines}
        demand = self._demand(request)
        ranked = sorted(
            (o for o in self._offers(machines) if eligible(o, demand)),
            key=offer_sort_key)
        return [by_id[o.offer_id] for o in ranked]

    async def can_host(self, request: ContainerRequest) -> bool:
        return bool(await self._eligible(request))

    async def add_worker(self, request: ContainerRequest) -> None:
        from ..repository.keys import Keys
        candidates = await self._eligible(request)
        if not candidates:
            log.warning("agent pool %s: no machine can host %s",
                        self.cfg.name, request.container_id)
            return
        # incr-then-check: two concurrent scale-ups (scheduler + pool
        # warmup) may both pass _eligible; the loser undoes its bump and
        # tries the next-cheapest machine, so desired can never wedge
        # above max
        for m in candidates:
            key = Keys.machine_desired(m["machine_id"])
            n = await self.store.incr(key)
            if n <= m["max_workers"]:
                log.info("agent pool %s: machine %s desired -> %d "
                         "(%.2f USD/h)", self.cfg.name, m["machine_id"], n,
                         int(m.get("hourly_cost_micros") or 0) / 1e6)
                await self._record_reservation(m, request)
                return
            await self.store.incr(key, by=-1, floor=0)
        log.warning("agent pool %s: all machines full for %s",
                    self.cfg.name, request.container_id)

    async def _record_reservation(self, machine: dict,
                                  request: ContainerRequest) -> None:
        """Rental bookkeeping (reference state.go:73-109): which offer a
        placement landed on and at what committed rate."""
        from ..repository.keys import Keys
        from ..types import new_id, now
        rid = new_id("resv")
        key = Keys.machine_reservations(self.cfg.name)
        await self.store.hset(key, rid, {
            "reservation_id": rid, "status": "active",
            "machine_id": machine["machine_id"],
            "container_id": request.container_id,
            "hourly_cost_micros": int(
                machine.get("hourly_cost_micros") or 0),
            "created_at": now()})
        # per-RECORD retention: a whole-hash TTL would be reset by every
        # placement (records accumulating forever on a busy pool) — prune
        # aged entries at insert instead
        cutoff = now() - self.RESERVATION_TTL_S
        stale = [f for f, v in (await self.store.hgetall(key)).items()
                 if float(v.get("created_at", 0)) < cutoff]
        if stale:
            await self.store.hdel(key, *stale)

    async def worker_count(self) -> int:
        total = 0
        for m in await self._machines():
            total += m["desired"]
        return total
