"""The slice scheduler.

Reference analogue: ``pkg/scheduler/scheduler.go`` — sorted-set backlog
(backlog.go:16), 50 ms batch loop popping up to 512 requests
(scheduler.go:28-33,589), filter+score selection, capacity reservation,
per-worker request streams, retry/requeue with failure accounting, pool
scale-up when nothing fits.

New beyond the reference: **gang scheduling** for multi-host slices — a
v5p-64 request atomically reserves all 16 hosts of one slice, stamps each
container with its gang rank/coordinator, and failure of any member stops the
others (shared fate).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import time
from typing import Optional

from ..config import SchedulerConfig
from ..repository import ContainerRepository, Keys, WorkerRepository
from ..statestore import StateStore
from ..types import (ContainerRequest, ContainerState, ContainerStatus,
                     GangInfo, StopReason, new_id)
from .pools import WorkerPoolController
from .selector import find_slice_gang, select_worker
from ..utils.aio import reap

log = logging.getLogger("tpu9.scheduler")


class SchedulingFailed(Exception):
    pass


class Scheduler:
    def __init__(self, store: StateStore, cfg: Optional[SchedulerConfig] = None,
                 pools: Optional[dict[str, WorkerPoolController]] = None,
                 quota=None):
        self.cfg = cfg or SchedulerConfig()
        self.store = store
        self.workers = WorkerRepository(store)
        self.containers = ContainerRepository(store)
        self.quota = quota        # Optional[QuotaService]
        self.pools = pools or {}
        self._task: Optional[asyncio.Task] = None
        self._stopping = asyncio.Event()
        self.stats = {"scheduled": 0, "retries": 0, "failed": 0,
                      "gangs_scheduled": 0, "pool_scaleups": 0}

    # -- public API ----------------------------------------------------------

    async def run(self, request: ContainerRequest) -> None:
        """Accept a placement request (reference Scheduler.Run,
        scheduler.go:367): persist + enqueue; the loop does the rest.
        Raises QuotaExceeded when the workspace is over its concurrency
        limit (scheduler.go:388's admission-time quota check)."""
        if not request.container_id:
            request.container_id = new_id("ct")
        if self.quota is not None:
            await self.quota.admit(request)
        request.timestamp = time.time()
        await self.containers.set_request(request)
        state = ContainerState(
            container_id=request.container_id, stub_id=request.stub_id,
            workspace_id=request.workspace_id,
            status=ContainerStatus.PENDING.value)
        await self.containers.update_state(state)
        await self._push_backlog(request)

    async def stop_container(self, container_id: str,
                             reason: str = StopReason.USER.value) -> bool:
        """Ask the owning worker to stop a container."""
        state = await self.containers.get_state(container_id)
        if state is None:
            return False
        if state.status == ContainerStatus.PENDING.value:
            # tombstone FIRST: the batch loop may have already popped this
            # id from the backlog (zrem below no-ops) and be about to
            # dispatch it — without the marker it would resurrect a
            # container the caller was just told is stopped, unmetered
            await self.store.set(Keys.container_tombstone(container_id),
                                 "1", ttl=600.0)
            await self.store.zrem(Keys.BACKLOG, container_id)
            await self.containers.delete_state(container_id, state.stub_id)
            return True
        await self.store.publish(f"container:stop:{state.worker_id}",
                                 {"container_id": container_id,
                                  "reason": reason})
        return True

    async def start(self) -> "Scheduler":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stopping.set()
        if self._task:
            # reap: swallows the child's CancelledError but re-raises if
            # stop() itself is cancelled mid-drain (ASY003)
            await reap(self._task)

    # -- backlog -------------------------------------------------------------

    async def _push_backlog(self, request: ContainerRequest) -> None:
        # score: priority first (lower score pops first), then FIFO by time
        score = -request.priority * 1e12 + request.timestamp
        await self.store.zadd(Keys.BACKLOG, request.container_id, score)

    async def backlog_depth(self) -> int:
        return await self.store.zcard(Keys.BACKLOG)

    # -- loop ----------------------------------------------------------------

    async def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                processed = await self._process_batch()
            except Exception:
                log.exception("scheduler batch failed")
                processed = 0
            if not processed:
                await asyncio.sleep(self.cfg.loop_interval_s)

    async def _process_batch(self) -> int:
        popped = await self.store.zpopmin(Keys.BACKLOG, self.cfg.batch_size)
        if not popped:
            return 0
        # zpopmin is DESTRUCTIVE: from here until each entry is scheduled
        # or re-added, a raised store error would strand the whole batch
        # PENDING forever — put unprocessed entries back on any failure
        remaining = {cid: score for cid, score in popped}
        try:
            now = time.time()
            workers = await self.workers.list()
            alive = await self.workers.alive_ids()
            processed = 0
            for container_id, score in popped:
                request = await self.containers.get_request(container_id)
                if request is None:
                    remaining.pop(container_id, None)
                    continue
                # retry entries carry a future not-before time folded into
                # the score (minus the priority offset); park them back
                # without consuming an attempt (backoff while pools
                # provision)
                not_before = score + request.priority * 1e12
                if not_before > now:
                    await self.store.zadd(Keys.BACKLOG, container_id, score)
                    remaining.pop(container_id, None)
                    continue
                remaining.pop(container_id, None)
                processed += 1
                try:
                    await self._schedule_one(request, workers, alive)
                except SchedulingFailed as exc:
                    await self._requeue(request, str(exc))
                except Exception as exc:   # one request must not drop batch
                    log.exception("scheduling %s errored",
                                  request.container_id)
                    await self._requeue(request, f"internal: {exc}")
            return processed
        except BaseException:
            for cid, score in remaining.items():
                try:
                    await self.store.zadd(Keys.BACKLOG, cid, score)
                except Exception:       # noqa: BLE001 — store still down;
                    pass                # the quota reconciler is backstop
            raise

    async def _schedule_one(self, request: ContainerRequest,
                            workers: list, alive: set[str]) -> None:
        from ..observability import tracer
        with tracer.span("scheduler.schedule",
                         trace_id=request.env.get("TPU9_TRACE_ID", ""),
                         attrs={"container_id": request.container_id,
                                "workspace_id": request.workspace_id,
                                "attempt": request.retry_count}):
            await self._schedule_one_traced(request, workers, alive)

    async def _schedule_one_traced(self, request: ContainerRequest,
                                   workers: list, alive: set[str]) -> None:
        if await self.store.get(
                Keys.container_tombstone(request.container_id)):
            # stop_container raced the backlog pop: the caller was told
            # "stopped" and the quota charge was released — dispatching
            # now would run an unmetered zombie
            log.info("dropping %s: stopped while pending",
                     request.container_id)
            return
        spec = request.tpu_spec()
        if spec is not None and spec.multi_host:
            await self._schedule_gang(request, workers, alive, spec)
            return

        worker = None
        if request.disk_affinity:
            # durable-disk placement: the worker holding the live disk dir
            # wins when it fits; otherwise any worker restores the snapshot
            preferred = [w for w in workers
                         if w.worker_id == request.disk_affinity]
            worker = select_worker(preferred, request, alive)
        if worker is None:
            worker = select_worker(workers, request, alive)
        if worker is None:
            await self._try_scale_up(request)
            raise SchedulingFailed("no eligible worker")

        chips = spec.chips_per_host if spec else 0
        ok = await self.workers.adjust_capacity(
            worker.worker_id, cpu_millicores=-request.cpu_millicores,
            memory_mb=-request.memory_mb, tpu_chips=-chips)
        if not ok:
            raise SchedulingFailed("capacity race lost")
        # keep the BATCH's in-memory snapshot honest: without this, every
        # later request in the same batch keeps picking this (now-full)
        # worker, losing the store-side capacity race and burning real
        # retry budget on phantom contention
        worker.free_cpu_millicores -= request.cpu_millicores
        worker.free_memory_mb -= request.memory_mb
        worker.tpu_free_chips -= chips

        try:
            await self._dispatch(worker.worker_id, request)
        except Exception as exc:
            # dispatch failed after capacity was reserved (state-store /
            # push error): release the reservation before the requeue, or
            # the capacity leaks until the worker re-registers
            await self.workers.adjust_capacity(
                worker.worker_id, cpu_millicores=request.cpu_millicores,
                memory_mb=request.memory_mb, tpu_chips=chips)
            raise SchedulingFailed(f"dispatch failed: {exc}") from exc

    async def _schedule_gang(self, request: ContainerRequest, workers: list,
                             alive: set[str], spec) -> None:
        members = find_slice_gang(workers, spec, request, alive)
        if members is None:
            await self._try_scale_up(request)
            raise SchedulingFailed(
                f"no {spec.name} slice with {spec.hosts} free hosts")

        gang_id = new_id("gang")
        reserved: list[str] = []
        per_host_chips = spec.chips_per_host
        try:
            for m in members:
                ok = await self.workers.adjust_capacity(
                    m.worker_id, cpu_millicores=-request.cpu_millicores,
                    memory_mb=-request.memory_mb, tpu_chips=-per_host_chips)
                if not ok:
                    raise SchedulingFailed(
                        f"gang reservation lost on {m.worker_id}")
                reserved.append(m.worker_id)
                m.free_cpu_millicores -= request.cpu_millicores
                m.free_memory_mb -= request.memory_mb
                m.tpu_free_chips -= per_host_chips
        except SchedulingFailed:
            # all-or-nothing: roll back partial reservations (store AND
            # the batch's in-memory snapshot)
            for worker_id in reserved:
                await self.workers.adjust_capacity(
                    worker_id, cpu_millicores=request.cpu_millicores,
                    memory_mb=request.memory_mb, tpu_chips=per_host_chips)
                for m in members:
                    if m.worker_id == worker_id:
                        m.free_cpu_millicores += request.cpu_millicores
                        m.free_memory_mb += request.memory_mb
                        m.tpu_free_chips += per_host_chips
            raise

        # rank 0's host is the jax coordinator; the port is derived from the
        # gang id so two gangs sharing a host never fight over one port
        coord_host = members[0].address.rsplit(":", 1)[0]
        coord_port = 8476 + (int(hashlib.sha1(gang_id.encode())
                                 .hexdigest(), 16) % 1000)
        coordinator = f"{coord_host}:{coord_port}"
        container_ids = [request.container_id] + [
            new_id("ct") for _ in range(1, len(members))]
        await self.store.hmset(Keys.gang(gang_id), {
            "size": len(members),
            "containers": json.dumps(container_ids),
            "stub_id": request.stub_id,
        })

        dispatched: list[tuple[str, str]] = []   # (worker_id, container_id)
        try:
            for rank, (m, container_id) in enumerate(zip(members,
                                                         container_ids)):
                member_req = ContainerRequest.from_dict(request.to_dict())
                member_req.container_id = container_id
                member_req.gang = GangInfo(
                    gang_id=gang_id, size=len(members), rank=rank,
                    peer_container_ids=container_ids,
                    coordinator_addr=coordinator)
                await self.containers.set_request(member_req)
                await self._dispatch(m.worker_id, member_req)
                dispatched.append((m.worker_id, container_id))
        except Exception as exc:
            # all-or-nothing extends through dispatch: stop members already
            # sent to workers, release reservations, drop the gang key, then
            # requeue the original request — otherwise earlier ranks run as a
            # half-gang while a duplicate gang gets scheduled later.
            # The id rename comes FIRST and each cleanup step is isolated:
            # a store outage mid-rollback must not requeue under an id whose
            # stop marker would cancel the rescheduled incarnation.
            dispatched_ids = {cid for _, cid in dispatched}
            old_id = request.container_id
            if old_id in dispatched_ids:
                # rank 0 (the original id) already reached a worker and will
                # be told to stop — recycle the requeued request under a
                # fresh id, leaving a redirect so clients that hold the
                # original id (pod create) can follow the reschedule
                request.container_id = new_id("ct")
                try:
                    await self.containers.set_redirect(old_id,
                                                       request.container_id)
                    if self.quota is not None:
                        await self.quota.rename(request.workspace_id,
                                                old_id,
                                                request.container_id)
                except Exception:
                    log.warning("gang rollback: redirect %s failed", old_id)
            for worker_id, container_id in dispatched:
                try:
                    await self.store.publish(
                        f"container:stop:{worker_id}",
                        {"container_id": container_id,
                         "reason": StopReason.SCHEDULER_FAILED.value})
                except Exception:
                    log.warning("gang rollback: stop %s on %s failed",
                                container_id, worker_id)
            # capacity: release only NON-dispatched members here — a request
            # that reached a worker's stream is released by that worker
            # (release-on-exit / failed-start path); releasing it twice would
            # over-credit a host that also runs unrelated containers
            for m, container_id in zip(members, container_ids):
                if container_id not in dispatched_ids:
                    try:
                        await self.workers.adjust_capacity(
                            m.worker_id,
                            cpu_millicores=request.cpu_millicores,
                            memory_mb=request.memory_mb,
                            tpu_chips=per_host_chips)
                    except Exception:
                        log.warning("gang rollback: release on %s failed "
                                    "(recovers at worker re-register)",
                                    m.worker_id)
            # drop phantom SCHEDULED state/request records for members no
            # worker will ever see (the failing rank and later ones)
            for container_id in container_ids:
                if (container_id not in dispatched_ids
                        and container_id != old_id):
                    try:
                        await self.containers.delete_state(container_id,
                                                           request.stub_id)
                    except Exception:
                        log.warning("gang rollback: state cleanup %s failed",
                                    container_id)
            try:
                await self.store.delete(Keys.gang(gang_id))
            except Exception:
                log.warning("gang rollback: gang key cleanup failed")
            raise SchedulingFailed(f"gang dispatch failed: {exc}") from exc
        self.stats["gangs_scheduled"] += 1

    async def _dispatch(self, worker_id: str, request: ContainerRequest) -> None:
        state = await self.containers.get_state(request.container_id)
        if state is None:
            state = ContainerState(container_id=request.container_id,
                                   stub_id=request.stub_id,
                                   workspace_id=request.workspace_id)
        state.status = ContainerStatus.SCHEDULED.value
        state.worker_id = worker_id
        state.scheduled_at = time.time()
        await self.containers.update_state(state)
        await self.workers.push_request(worker_id, request)
        self.stats["scheduled"] += 1

    async def _requeue(self, request: ContainerRequest, reason: str) -> None:
        request.retry_count += 1
        if request.retry_count > self.cfg.max_retries:
            log.warning("giving up on %s after %d attempts (%s)",
                        request.container_id, request.retry_count, reason)
            self.stats["failed"] += 1
            state = await self.containers.get_state(request.container_id)
            if state:
                state.status = ContainerStatus.FAILED.value
                state.stop_reason = StopReason.SCHEDULER_FAILED.value
                await self.containers.update_state(state)
            else:
                # the 60s state TTL can lapse while a request waits out
                # pool provisioning — the quota charge must release anyway
                await self.containers.release_quota_charge(
                    request.workspace_id, request.container_id)
            await self.containers.set_exit_code(
                request.container_id, -1,
                f"{StopReason.SCHEDULER_FAILED.value}: {reason}")
            return
        self.stats["retries"] += 1
        await self.containers.set_request(request)
        # exponential not-before backoff (pool provisioning takes seconds to
        # minutes; reference: provisioning_backoff.go), preserving the
        # priority component of the original score
        delay = min(0.25 * (1.7 ** request.retry_count), 15.0)
        score = -request.priority * 1e12 + time.time() + delay
        await self.store.zadd(Keys.BACKLOG, request.container_id, score)

    async def _try_scale_up(self, request: ContainerRequest) -> None:
        for name, pool in self.pools.items():
            if request.pool_selector and name != request.pool_selector:
                continue
            if await pool.can_host(request):
                try:
                    await pool.add_worker(request)
                    self.stats["pool_scaleups"] += 1
                    return
                except Exception as exc:
                    log.warning("pool %s scale-up failed: %s", name, exc)
