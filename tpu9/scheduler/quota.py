"""Per-workspace concurrency quotas: chips and CPU in flight.

Reference analogue: ``pkg/api/v1/concurrencylimit.go`` +
``scheduler.go:388-393`` (``SetContainerStateWithConcurrencyLimit``) — an
operator caps a workspace's concurrent GPU/CPU footprint; requests over
the cap are rejected at admission, before they ever reach the backlog.
tpu9 meters TPU chips instead of GPUs, and a multi-host (gang) request is
charged its FULL slice cost up front — all hosts' chips, not rank 0's.

Accounting lives in one hot hash per workspace (``ws:active:<id>``:
container_id → "cpu:chips") added at admission and removed on every
terminal path through ``ContainerRepository.release_quota_charge`` — the
same hot-state-with-TTL'd-truth pattern the rest of the scheduler uses.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..repository.keys import Keys
from ..types import ContainerRequest

log = logging.getLogger("tpu9.scheduler")

# a charge with no live container AND no backlog entry older than this is
# orphaned (worker host died before any terminal event could fire)
RECONCILE_GRACE_S = 120.0


class QuotaExceeded(Exception):
    def __init__(self, what: str, in_use: int, limit: int, asking: int):
        super().__init__(
            f"workspace {what} quota exceeded: {in_use} in use + "
            f"{asking} requested > limit {limit}")
        self.what = what


def request_cost(request: ContainerRequest) -> tuple[int, int]:
    """(cpu_millicores, tpu_chips) a request will occupy — the WHOLE slice
    for multi-host specs (every gang member runs cpu/memory too, but the
    defining quota unit is chips; cpu is charged once per request like the
    reference's CPUMillicoreLimit)."""
    spec = request.tpu_spec()
    chips = spec.chips if spec else 0
    return request.cpu_millicores, chips


class QuotaService:
    def __init__(self, store, backend):
        self.store = store
        self.backend = backend

    async def admit(self, request: ContainerRequest) -> None:
        """Charge the request against its workspace's limits; raises
        QuotaExceeded (leaving no accounting entry) when over. The
        read-check-charge runs under a per-workspace store lock — two
        concurrent admissions must not both observe the pre-charge total
        and jointly blow the cap."""
        limit = await self.backend.get_concurrency_limit(
            request.workspace_id)
        cpu, chips = request_cost(request)
        if limit is None:
            await self.store.hset(
                Keys.workspace_active(request.workspace_id),
                request.container_id, f"{cpu}:{chips}:{int(time.time())}")
            return

        import asyncio

        from ..types import new_id
        lock_key = f"wsquota:{request.workspace_id}"
        token = new_id("qtok")
        for _ in range(100):
            if await self.store.acquire_lock(lock_key, token, ttl=5.0):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(
                f"could not lock quota for {request.workspace_id}")
        try:
            in_use_cpu, in_use_chips = await self.in_use(
                request.workspace_id)
            chip_limit = int(limit.get("tpu_chip_limit") or 0)
            cpu_limit = int(limit.get("cpu_millicore_limit") or 0)
            if chip_limit and in_use_chips + chips > chip_limit:
                raise QuotaExceeded("tpu_chip", in_use_chips, chip_limit,
                                    chips)
            if cpu_limit and in_use_cpu + cpu > cpu_limit:
                raise QuotaExceeded("cpu_millicore", in_use_cpu, cpu_limit,
                                    cpu)
            await self.store.hset(
                Keys.workspace_active(request.workspace_id),
                request.container_id, f"{cpu}:{chips}:{int(time.time())}")
        finally:
            await self.store.release_lock(lock_key, token)

    async def rename(self, workspace_id: str, old_id: str,
                     new_id: str) -> None:
        """Gang rollback recycles a request under a fresh id — move its
        charge so the terminal cleanup of the OLD id doesn't strand it."""
        key = Keys.workspace_active(workspace_id)
        cost = await self.store.hget(key, old_id)
        if cost is not None:
            await self.store.hdel(key, old_id)
            await self.store.hset(key, new_id, cost)

    async def in_use(self, workspace_id: str) -> tuple[int, int]:
        entries = await self.store.hgetall(
            Keys.workspace_active(workspace_id))
        cpu = chips = 0
        for cost in (entries or {}).values():
            parts = str(cost).split(":")
            try:
                cpu += int(parts[0])
                chips += int(parts[1])
            except (ValueError, IndexError):
                continue
        return cpu, chips

    async def reconcile(self) -> int:
        """Release charges whose container no longer exists anywhere — not
        as live state, not in the backlog — and is past the grace window.
        Covers the ungraceful path (worker host dies, state key TTLs out,
        no terminal event ever fires) that would otherwise inflate
        ``in_use`` forever. Returns the number of charges released."""
        released = 0
        prefix = Keys.workspace_active("")
        for key in await self.store.keys(prefix + "*"):
            for cid, cost in (await self.store.hgetall(key) or {}).items():
                parts = str(cost).split(":")
                ts = float(parts[2]) if len(parts) > 2 else 0.0
                if time.time() - ts < RECONCILE_GRACE_S:
                    continue
                if await self.store.exists(Keys.container_state(cid)):
                    continue
                if await self.store.zscore(Keys.BACKLOG, cid) is not None:
                    continue
                released += await self.store.hdel(key, cid)
        if released:
            log.info("quota reconcile released %d orphaned charges",
                     released)
        return released
