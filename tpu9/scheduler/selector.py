"""Worker selection: filter chain + scored bin-packing.

Reference analogue: ``pkg/scheduler/scheduler.go:1012-1176``
(filterWorkersByPoolSelector/Resources, scheduleRequest's status-ordered
scoring). The TPU twist: requests carry slice shapes, so the resource filter
matches generation + per-host chip count, and multi-host requests filter to
slice members (handled by the gang path in scheduler.py).
"""

from __future__ import annotations

from typing import Optional

from ..types import ContainerRequest, TpuSpec, WorkerState, WorkerStatus


def filter_workers(workers: list[WorkerState], request: ContainerRequest,
                   alive: Optional[set[str]] = None) -> list[WorkerState]:
    spec = request.tpu_spec()
    out = []
    for w in workers:
        if w.status not in (WorkerStatus.AVAILABLE.value,):
            continue
        if alive is not None and w.worker_id not in alive:
            continue
        if request.pool_selector and w.pool != request.pool_selector:
            continue
        if w.free_cpu_millicores < request.cpu_millicores:
            continue
        if w.free_memory_mb < request.memory_mb:
            continue
        if spec is not None:
            if w.tpu_generation != spec.generation:
                continue
            if w.tpu_free_chips < spec.chips_per_host:
                continue
            # single-host slices must fit one host entirely
            if spec.hosts == 1 and w.tpu_chip_count < spec.chips:
                continue
        else:
            # CPU request: don't burn TPU hosts unless pool-pinned
            if w.tpu_chip_count > 0 and not request.pool_selector:
                continue
        out.append(w)
    return out


def score_worker(w: WorkerState, request: ContainerRequest) -> float:
    """Higher is better. Bin-pack: prefer the tightest fit (least leftover
    chips, then least leftover cpu), prefer higher-priority pools, and prefer
    workers already warm (fewer free == more packed)."""
    spec = request.tpu_spec()
    score = float(w.priority) * 1000.0
    if spec is not None:
        leftover_chips = w.tpu_free_chips - spec.chips_per_host
        score -= leftover_chips * 100.0
    leftover_cpu = w.free_cpu_millicores - request.cpu_millicores
    score -= leftover_cpu / 1000.0
    leftover_mem = w.free_memory_mb - request.memory_mb
    score -= leftover_mem / 10240.0
    return score


def select_worker(workers: list[WorkerState], request: ContainerRequest,
                  alive: Optional[set[str]] = None) -> Optional[WorkerState]:
    candidates = filter_workers(workers, request, alive)
    if not candidates:
        return None
    return max(candidates, key=lambda w: score_worker(w, request))


def find_slice_gang(workers: list[WorkerState], spec: TpuSpec,
                    request: ContainerRequest,
                    alive: Optional[set[str]] = None) -> Optional[list[WorkerState]]:
    """Find a full slice (all hosts sharing one slice_id) that can host a
    multi-host gang. All-or-nothing: every member host must pass the filters.
    No reference analogue — the reference schedules single workers only."""
    by_slice: dict[str, list[WorkerState]] = {}
    for w in workers:
        if w.slice_id and w.tpu_generation == spec.generation:
            by_slice.setdefault(w.slice_id, []).append(w)

    for slice_id, members in sorted(by_slice.items()):
        if len(members) != spec.hosts:
            continue
        if any(m.slice_host_count != spec.hosts for m in members):
            continue
        eligible = filter_workers(members, request, alive)
        if len(eligible) == len(members):
            return sorted(members, key=lambda m: m.slice_host_rank)
    return None
