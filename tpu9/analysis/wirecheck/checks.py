"""wirecheck rules (ISSUE 18): contracts.toml vs the extracted surfaces.

Five rules over four wire surfaces:

- WIR001 — stats/heartbeat field agreement per declared surface: phantom
  consumer reads (error), contract entries nothing produces (error),
  producer writes the contract does not know (error), and
  produced-but-never-consumed dead telemetry (warn tier).
- WIR002 — ``tpu9_*`` metric names: asserted-but-never-emitted drift
  (error), per-replica gauge families without ``remove_gauge`` coverage
  (error — the PR 14 unbounded-cardinality class), emitted-but-never-
  asserted (warn tier).
- KEY001 — store key namespaces: undeclared namespace (error),
  cross-plane writes (error), plain ``set`` on an atomic namespace
  (error — the postmortem RMW class), TTL-less writes where the
  namespace requires TTL discipline (error).
- ENV001 — ``TPU9_*`` env reads: undeclared var (error), reader outside
  the declared set (error), divergent inline defaults (error).
- RPC001 — route agreement: registered-but-never-called (error unless
  declared external), called-but-never-registered (error), bench_guard
  ``HARD_FIELDS`` a bench phase cannot emit (error), guarded fields
  absent from bench.py (warn tier).

Errors gate; warns report. Both carry the shared finding schema.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..findings import Finding
from . import extract as ex


@dataclass
class SurfaceSpec:
    name: str
    fields: list = field(default_factory=list)
    families: list = field(default_factory=list)
    synthetic: list = field(default_factory=list)
    dead_ok: dict = field(default_factory=dict)        # key -> reason
    manual_consumed: dict = field(default_factory=dict)
    producers: list = field(default_factory=list)      # (path, qual, var)
    consumers: list = field(default_factory=list)
    consumer_lists: list = field(default_factory=list)  # (path, const)


@dataclass
class KeySpec:
    name: str
    pattern: str
    writers: list = field(default_factory=list)
    ttl: str = "optional"          # "required" | "optional" | "none"
    atomic: bool = False


@dataclass
class WireContracts:
    surfaces: list = field(default_factory=list)
    keys: list = field(default_factory=list)
    env: dict = field(default_factory=dict)      # var -> [reader prefixes]
    env_divergent_ok: dict = field(default_factory=dict)
    metric_entity_labels: list = field(default_factory=list)
    metric_assert_ok: dict = field(default_factory=dict)
    metric_remove_ok: dict = field(default_factory=dict)
    metric_dynamic_prefixes: list = field(default_factory=list)
    rpc_external_ok: dict = field(default_factory=dict)
    rpc_call_only_ok: dict = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "WireContracts":
        from .. import tomlmini
        raw = tomlmini.load_file(path)
        c = cls()
        for name, t in raw.get("surface", {}).items():
            s = SurfaceSpec(name=name)
            s.fields = list(t.get("fields", []))
            s.families = list(t.get("families", []))
            s.synthetic = list(t.get("synthetic", []))
            s.dead_ok = _reasons(t.get("dead_ok", []))
            s.manual_consumed = _reasons(t.get("manual_consumed", []))
            s.producers = [_scope3(e) for e in t.get("producers", [])]
            s.consumers = [_scope3(e) for e in t.get("consumers", [])]
            s.consumer_lists = [_scope2(e)
                                for e in t.get("consumer_lists", [])]
            c.surfaces.append(s)
        for name, t in raw.get("keys", {}).items():
            c.keys.append(KeySpec(
                name=name, pattern=t.get("pattern", name + ":*"),
                writers=list(t.get("writers", [])),
                ttl=t.get("ttl", "optional"),
                atomic=bool(t.get("atomic", False))))
        for var, t in raw.get("env", {}).items():
            c.env[var] = list(t.get("readers", []))
            if t.get("divergent_ok"):
                c.env_divergent_ok[var] = t["divergent_ok"]
        m = raw.get("metrics", {})
        c.metric_entity_labels = list(m.get("entity_labels", []))
        c.metric_assert_ok = _reasons(m.get("assert_ok", []))
        c.metric_remove_ok = _reasons(m.get("remove_ok", []))
        c.metric_dynamic_prefixes = list(m.get("dynamic_prefixes", []))
        r = raw.get("rpc", {})
        c.rpc_external_ok = _reasons(r.get("external_ok", []))
        c.rpc_call_only_ok = _reasons(r.get("call_only_ok", []))
        return c


def _reasons(entries) -> dict:
    """``["name: why", ...]`` -> {name: why}; a missing reason is an
    authoring error surfaced loudly at load."""
    out = {}
    for e in entries:
        name, _, reason = e.partition(":")
        if not reason.strip():
            raise ValueError(
                f"contracts.toml exemption {e!r} has no reason — every "
                "allowance must say why (\"name: reason\")")
        out[name.strip()] = reason.strip()
    return out


def _scope3(entry: str):
    parts = entry.split("::")
    if len(parts) != 3:
        raise ValueError(
            f"contracts.toml scope {entry!r} must be path::qualname::var")
    return tuple(parts)


def _scope2(entry: str):
    parts = entry.split("::")
    if len(parts) != 2:
        raise ValueError(
            f"contracts.toml list-consumer {entry!r} must be path::CONST")
    return tuple(parts)


# marker for fixture-corpus files (must appear in the first 2 KiB)
FIXTURE_PRAGMA = "tpu9: wirecheck-fixture-corpus"


class CheckContext:
    """One repo scan shared by every rule: per-file module indexes plus
    the global metric/store/env/route inventories."""

    def __init__(self, repo_root: str, contracts: WireContracts,
                 contracts_path: str):
        self.repo_root = repo_root
        self.contracts = contracts
        self.contracts_path = contracts_path
        # findings anchor to the repo-relative path so fingerprints are
        # stable across checkouts
        rel = os.path.relpath(contracts_path, repo_root)
        self.contracts_rel = rel.replace(os.sep, "/")
        self.indexes: dict[str, ex.ModuleIndex] = {}
        self.parse_errors: list[str] = []
        self.metric_emits: list[ex.MetricUse] = []
        self.metric_removes: list[ex.MetricUse] = []
        self.metric_asserts: list[ex.MetricUse] = []
        self.store_ops: list[ex.StoreOp] = []
        self.env_reads: list[ex.EnvRead] = []
        self.routes_registered: list[ex.RouteUse] = []
        self.route_calls: list[ex.RouteUse] = []
        self.bench_literals: set[str] = set()
        self.guard_fields: dict = {}     # from scripts/bench_guard.py
        self.hard_fields: tuple = ()

    # role predicates — which inventory a file feeds
    @staticmethod
    def _is_test(path: str) -> bool:
        return path.startswith("tests/")

    def _fixture_corpus(self, rel: str) -> bool:
        """Files that opt out of inventory extraction entirely: their
        strings are *about* wire surfaces (checker fixtures, seeded
        violations), not uses of them."""
        try:
            with open(os.path.join(self.repo_root, rel),
                      encoding="utf-8") as fh:
                head = fh.read(2048)
        except OSError:
            return False
        return FIXTURE_PRAGMA in head

    @staticmethod
    def _asserts_metrics(path: str) -> bool:
        return (path.startswith("tests/") or path.startswith("tpu9/cli/")
                or path.startswith("scripts/"))

    def index(self, rel_path: str) -> "ex.ModuleIndex | None":
        idx = self.indexes.get(rel_path)
        if idx is None and rel_path not in self.parse_errors:
            idx = ex.index_module(self.repo_root, rel_path)
            if idx is None:
                self.parse_errors.append(rel_path)
                return None
            self.indexes[rel_path] = idx
        return idx

    def scan(self, rel_paths: list[str]) -> None:
        for rel in rel_paths:
            if self._fixture_corpus(rel):
                continue
            idx = self.index(rel)
            if idx is None:
                continue
            if rel.startswith("tpu9/"):
                for use in ex.extract_metrics(idx):
                    (self.metric_removes if use.method == "remove_gauge"
                     else self.metric_emits).append(use)
                self.store_ops.extend(ex.extract_store_ops(idx))
                if not rel.startswith("tpu9/analysis/"):
                    # the checker's own sources mention route prefixes as
                    # data, not as calls
                    reg, called = ex.extract_routes(idx)
                    self.routes_registered.extend(reg)
                    self.route_calls.extend(called)
            else:
                _, called = ex.extract_routes(idx)
                self.route_calls.extend(called)
            if self._asserts_metrics(rel):
                self.metric_asserts.extend(ex.extract_metric_literals(idx))
            if not self._is_test(rel):
                self.env_reads.extend(ex.extract_env_reads(idx))
            if rel == "bench.py":
                for node in __import__("ast").walk(idx.tree):
                    lit = ex._lit_str(node)
                    if lit is not None:
                        self.bench_literals.add(lit)
            if rel == "scripts/bench_guard.py":
                self.hard_fields = tuple(
                    e for e in idx.consts.get("HARD_FIELDS", ())
                    if isinstance(e, str))
                # GUARDED_FIELDS is a dict literal — pull keys by AST
                self.guard_fields = _dict_const_keys(idx, "GUARDED_FIELDS")

    def contracts_site(self, needle: str) -> tuple[int, int]:
        """Line of the first contracts.toml line containing ``needle`` —
        an anchor for contract-side findings."""
        try:
            with open(self.contracts_path, encoding="utf-8") as f:
                for i, line in enumerate(f, start=1):
                    if needle in line:
                        return i, line.index(needle)
        except OSError:
            pass
        return 1, 0


def _dict_const_keys(idx: ex.ModuleIndex, name: str) -> dict:
    import ast
    for node in ast.walk(idx.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Dict) and \
                any(isinstance(t, ast.Name) and t.id == name
                    for t in node.targets):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                key = ex._lit_str(k)
                if key is not None:
                    out[key] = ex._lit_str(v)
            return out
    return {}


def _f(rule, site: ex.Site, message: str, symbol: str) -> Finding:
    return Finding(rule, site.path, site.line, site.col, message,
                   symbol=symbol)


# -- WIR001 ------------------------------------------------------------------

def check_surfaces(ctx: CheckContext) -> tuple[list[Finding],
                                               list[Finding]]:
    findings, warns = [], []
    for spec in ctx.contracts.surfaces:
        f, w = _check_surface(ctx, spec)
        findings += f
        warns += w
    return findings, warns


def _check_surface(ctx: CheckContext, spec: SurfaceSpec):
    findings: list[Finding] = []
    warns: list[Finding] = []
    produced: dict[str, ex.Site] = {}
    produced_fams: dict[str, ex.Site] = {}
    reads: list[ex.KeyUse] = []

    for path, qual, var in spec.producers:
        idx = ctx.index(path)
        sk = ex.extract_scope_keys(idx, qual, var, producer=True) \
            if idx else None
        if sk is None:
            findings.append(Finding(
                "WIR001", ctx.contracts_rel,
                *ctx.contracts_site(qual),
                f"surface '{spec.name}': producer scope "
                f"{path}::{qual} not found — contracts.toml is stale",
                symbol=f"{spec.name}.producer.{qual}"))
            continue
        for use in sk.writes:
            (produced_fams if use.family else produced).setdefault(
                use.key, use.site)
    for path, qual, var in spec.consumers:
        idx = ctx.index(path)
        sk = ex.extract_scope_keys(idx, qual, var, producer=False) \
            if idx else None
        if sk is None:
            findings.append(Finding(
                "WIR001", ctx.contracts_rel,
                *ctx.contracts_site(qual),
                f"surface '{spec.name}': consumer scope "
                f"{path}::{qual} not found — contracts.toml is stale",
                symbol=f"{spec.name}.consumer.{qual}"))
            continue
        reads.extend(sk.reads)
    for path, const in spec.consumer_lists:
        idx = ctx.index(path)
        keys = ex.extract_const_list(idx, const) if idx else []
        if not keys:
            findings.append(Finding(
                "WIR001", ctx.contracts_rel,
                *ctx.contracts_site(const),
                f"surface '{spec.name}': consumer list {path}::{const} "
                "not found or empty — contracts.toml is stale",
                symbol=f"{spec.name}.consumer_list.{const}"))
            continue
        line = idx.consts_lineno.get(const, 1)
        for key in keys:
            reads.append(ex.KeyUse(key, ex.Site(path, line, 0, const)))

    declared = set(spec.fields) | set(spec.synthetic)
    produced_all = set(produced) | set(spec.synthetic)

    def _produced(key: str) -> bool:
        return key in produced_all or \
            any(key.startswith(p) for p in produced_fams)

    def _declared(key: str) -> bool:
        return key in declared or \
            any(key.startswith(p) for p in spec.families)

    # phantom consumer: a read no producer satisfies
    for use in reads:
        if use.family:
            ok = use.key in produced_fams or \
                any(k.startswith(use.key) for k in produced_all)
            if not ok:
                findings.append(_f(
                    "WIR001", use.site,
                    f"surface '{spec.name}': consumer reads the "
                    f"'{use.key}*' family but no producer writes it — "
                    "the reads silently see nothing", use.key))
        elif not _produced(use.key):
            findings.append(_f(
                "WIR001", use.site,
                f"surface '{spec.name}': consumer reads '{use.key}' but "
                "no producer writes it — the read silently defaults",
                use.key))

    # contract rot: declared field nothing produces
    for key in spec.fields:
        if not _produced(key):
            findings.append(Finding(
                "WIR001", ctx.contracts_rel, *ctx.contracts_site(key),
                f"surface '{spec.name}': contract declares '{key}' but "
                "no producer writes it — fix the producer or prune the "
                "contract", symbol=f"{spec.name}.{key}"))

    # undeclared production: a write the contract does not know
    for key, site in produced.items():
        if not _declared(key):
            findings.append(_f(
                "WIR001", site,
                f"surface '{spec.name}': producer writes '{key}' but "
                "contracts.toml does not declare it — add it to the "
                "surface field list (and a consumer, or dead_ok)", key))
    for fam, site in produced_fams.items():
        if fam not in spec.families:
            findings.append(_f(
                "WIR001", site,
                f"surface '{spec.name}': producer writes the '{fam}*' "
                "family but contracts.toml does not declare it in "
                "families", fam))

    # dead telemetry (warn tier): produced, declared, nobody reads it
    read_exact = {u.key for u in reads if not u.family}
    read_fams = {u.key for u in reads if u.family}
    consumed_extra = set(spec.manual_consumed)

    def _consumed(key: str) -> bool:
        return key in read_exact or key in consumed_extra or \
            any(key.startswith(p) for p in read_fams)

    for key in sorted(produced_all):
        if _declared(key) and not _consumed(key) \
                and key not in spec.dead_ok:
            site = produced.get(key)
            if site is None:
                line, col = ctx.contracts_site(key)
                site = ex.Site(ctx.contracts_rel, line, col, spec.name)
            warns.append(_f(
                "WIR001", site,
                f"surface '{spec.name}': '{key}' is produced but no "
                "declared consumer reads it — dead telemetry (add a "
                "consumer, or a dead_ok entry with a reason)", key))
    return findings, warns


# -- WIR002 ------------------------------------------------------------------

def check_metrics(ctx: CheckContext) -> tuple[list[Finding],
                                              list[Finding]]:
    findings, warns = [], []
    c = ctx.contracts
    emitted = {u.name for u in ctx.metric_emits if not u.family}
    emitted_fams = {u.name for u in ctx.metric_emits if u.family} \
        | set(c.metric_dynamic_prefixes)
    removed = {u.name for u in ctx.metric_removes if not u.family}
    removed_fams = {u.name for u in ctx.metric_removes if u.family}

    def _emitted(name: str) -> bool:
        return name in emitted or \
            any(name.startswith(p) for p in emitted_fams)

    # asserted-but-never-emitted: a test/CLI/guard naming a ghost series
    seen_assert: set[tuple] = set()
    for use in ctx.metric_asserts:
        if _emitted(use.name) or (use.name, use.site.path) in seen_assert:
            continue
        seen_assert.add((use.name, use.site.path))
        findings.append(_f(
            "WIR002", use.site,
            f"'{use.name}' is asserted here but nothing in tpu9/ emits "
            "it — the assertion tests a ghost series", use.name))

    # per-entity gauges need remove_gauge coverage (PR 14 class)
    entity = set(c.metric_entity_labels)
    seen_gauge: set[str] = set()
    for use in ctx.metric_emits:
        if use.method != "set_gauge" or use.name in seen_gauge:
            continue
        if not (entity & set(use.label_keys)):
            continue
        seen_gauge.add(use.name)
        covered = use.name in removed or \
            any(use.name.startswith(p) for p in removed_fams) or \
            (use.family and use.name in removed_fams)
        if not covered and use.name not in c.metric_remove_ok:
            label = sorted(entity & set(use.label_keys))[0]
            findings.append(_f(
                "WIR002", use.site,
                f"per-{label} gauge '{use.name}{'*' if use.family else ''}'"
                " has no remove_gauge coverage — dead entities keep their "
                "last value forever and the series set grows without "
                "bound under churn", use.name))

    # emitted-but-never-asserted (warn tier)
    asserted = {u.name for u in ctx.metric_asserts}
    for use in ctx.metric_emits:
        if use.family or use.name in asserted or \
                use.name in c.metric_assert_ok:
            continue
        if any(use.name.startswith(p) and p in asserted
               for p in emitted_fams):
            continue
        asserted.add(use.name)     # one warn per name
        warns.append(_f(
            "WIR002", use.site,
            f"'{use.name}' is emitted but never asserted in tests/CLI — "
            "unwatched telemetry (assert it somewhere, or add an "
            "assert_ok entry with a reason)", use.name))
    return findings, warns


# -- KEY001 ------------------------------------------------------------------

def check_store_keys(ctx: CheckContext) -> tuple[list[Finding],
                                                 list[Finding]]:
    findings: list[Finding] = []
    specs = ctx.contracts.keys

    def _spec_for(key: str):
        best = None
        for s in specs:
            pat = s.pattern
            if pat.endswith("*"):
                if key.startswith(pat[:-1]) or key == pat[:-1].rstrip(":"):
                    if best is None or len(pat) > len(best.pattern):
                        best = s
            elif key == pat:
                return s
        return best

    seen_undeclared: set[tuple] = set()
    for op in ctx.store_ops:
        spec = _spec_for(op.key)
        if spec is None:
            k = (op.key, op.site.path)
            if k not in seen_undeclared:
                seen_undeclared.add(k)
                findings.append(_f(
                    "KEY001", op.site,
                    f"store key '{op.key}' matches no namespace declared "
                    "in contracts.toml — declare its writer plane, TTL "
                    "discipline and atomicity", op.key))
            continue
        if op.op in ex.STORE_WRITE_OPS:
            if spec.writers and not any(
                    op.site.path.startswith(w) for w in spec.writers):
                findings.append(_f(
                    "KEY001", op.site,
                    f"'{op.op}' on '{op.key}' from {op.site.path} — "
                    f"namespace '{spec.name}' declares writers "
                    f"{spec.writers}; cross-plane writes race the owner",
                    op.key))
            if spec.atomic and op.op in ("set", "hset", "hmset"):
                findings.append(_f(
                    "KEY001", op.site,
                    f"plain '{op.op}' on atomic namespace '{spec.name}' "
                    f"('{op.key}') — multi-writer keys must use the "
                    "atomic list/CAS ops (rpush/ltrim/cas); read-modify-"
                    "write erases concurrent writes", op.key))
            if spec.ttl == "required" and not op.has_ttl and \
                    op.op in ("set", "hset", "hmset") and \
                    not _expire_in_scope(ctx, op):
                findings.append(_f(
                    "KEY001", op.site,
                    f"TTL-less '{op.op}' on '{op.key}' — namespace "
                    f"'{spec.name}' requires TTL discipline (pass ttl= "
                    "or expire() in the same scope); an unreaped key "
                    "leaks state forever", op.key))
    return findings, []


def _expire_in_scope(ctx: CheckContext, op: ex.StoreOp) -> bool:
    prefix = op.key.split("*")[0]
    return any(o.op == "expire" and o.site.path == op.site.path
               and o.site.symbol == op.site.symbol
               and o.key.split("*")[0] == prefix
               for o in ctx.store_ops)


# -- ENV001 ------------------------------------------------------------------

# reads here are the *point* of the rule — the accessor every other
# plane is told to route through — so they are implicitly declared
ENV_HOME = "tpu9/config.py"


def check_env(ctx: CheckContext) -> tuple[list[Finding], list[Finding]]:
    findings: list[Finding] = []
    declared = ctx.contracts.env
    by_var: dict[str, list[ex.EnvRead]] = {}
    for r in ctx.env_reads:
        by_var.setdefault(r.var, []).append(r)
    for var, uses in sorted(by_var.items()):
        readers = declared.get(var)
        if readers is None:
            for use in uses:
                if use.site.path == ENV_HOME:
                    continue    # the canonical accessor home needs no entry
                findings.append(_f(
                    "ENV001", use.site,
                    f"'{var}' is read here but not declared in "
                    "contracts.toml [env] — route it through "
                    "tpu9/config.py or declare its reader", var))
            continue
        for use in uses:
            if use.site.path == ENV_HOME:
                continue
            if not any(use.site.path.startswith(r) for r in readers):
                findings.append(_f(
                    "ENV001", use.site,
                    f"'{var}' read outside its declared readers "
                    f"{readers} — a second reader grows a second "
                    "default; route through tpu9/config.py", var))
        defaults = {u.default for u in uses}
        if len(defaults) > 1 and var not in ctx.contracts.env_divergent_ok:
            site = sorted(uses, key=lambda u: (u.site.path,
                                               u.site.line))[-1].site
            findings.append(_f(
                "ENV001", site,
                f"'{var}' has divergent inline defaults across its "
                f"readers: {sorted(defaults)} — the effective value "
                "depends on which plane asks; hoist one default into "
                "tpu9/config.py", var))
    return findings, []


# -- RPC001 ------------------------------------------------------------------

def check_rpc(ctx: CheckContext) -> tuple[list[Finding], list[Finding]]:
    findings: list[Finding] = []
    warns: list[Finding] = []
    c = ctx.contracts
    seen: set[str] = set()
    for reg in ctx.routes_registered:
        if reg.pattern in seen:
            continue
        seen.add(reg.pattern)
        called = any(ex.route_match(reg.pattern, call.pattern)
                     for call in ctx.route_calls)
        if not called and reg.pattern not in c.rpc_external_ok:
            findings.append(_f(
                "RPC001", reg.site,
                f"route '{reg.pattern}' is registered but nothing in the "
                "repo calls it — dead handler (or declare it external_ok "
                "with a reason)", reg.pattern))
    seen_calls: set[tuple] = set()
    for call in ctx.route_calls:
        key = (call.pattern, call.site.path)
        if key in seen_calls:
            continue
        seen_calls.add(key)
        handled = any(ex.route_match(reg.pattern, call.pattern)
                      for reg in ctx.routes_registered)
        if not handled and call.pattern not in c.rpc_call_only_ok:
            findings.append(_f(
                "RPC001", call.site,
                f"'{call.pattern}' is called here but no handler "
                "registers it — the call can only 404", call.pattern))
    # bench_guard cross-check: a HARD field bench.py cannot emit would
    # make every future round a guaranteed guard failure
    for fld in ctx.hard_fields:
        if fld not in ctx.bench_literals:
            findings.append(Finding(
                "RPC001", "scripts/bench_guard.py", 1, 0,
                f"HARD field '{fld}' does not appear in bench.py — no "
                "phase can emit it, so its presence check can never "
                "pass", symbol=fld))
    for fld in ctx.guard_fields:
        if fld not in ctx.bench_literals:
            warns.append(Finding(
                "RPC001", "scripts/bench_guard.py", 1, 0,
                f"guarded field '{fld}' does not appear in bench.py — "
                "the guard entry is dead weight", symbol=fld))
    return findings, warns


ALL_CHECKS = {
    "WIR001": check_surfaces,
    "WIR002": check_metrics,
    "KEY001": check_store_keys,
    "ENV001": check_env,
    "RPC001": check_rpc,
}
