"""AST extraction for wirecheck (ISSUE 18).

Everything here is *positive-evidence* extraction: a key/name/route is
collected only when it appears in a syntactic position that ties it to a
wire surface (a read off a declared dict variable, the name argument of a
metrics call, the key argument of a state-store op, …). Bare string
literals never count on their own — that is what keeps the checker's
false-positive rate near zero on a repo that is full of strings.

The extractors are deliberately scope-driven: ``contracts.toml`` names the
producer and consumer scopes as ``path::qualname::var`` and extraction
happens only inside those scopes, against that variable. A consumer
function that also touches three other payload dicts contributes nothing
from them.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

KEY_RE = re.compile(r"^[a-z][a-z0-9_]+$")
METRIC_RE = re.compile(r"^tpu9_[a-z0-9_]+$")
METRIC_METHODS = ("inc", "observe", "set_gauge", "remove_gauge")
# ops that CREATE/overwrite state (writer-plane checked); pops/trims/
# deletes/expires are consumer-side lifecycle and stay exempt
STORE_WRITE_OPS = ("set", "hset", "hmset", "rpush", "lpush", "incr",
                   "hincr", "cas")
STORE_READ_OPS = ("get", "hget", "hgetall", "lrange", "llen", "keys",
                  "exists", "blpop", "lpop")
STORE_LIFECYCLE_OPS = ("delete", "expire", "ltrim", "lrem", "hdel",
                       "acquire_lock", "release_lock")
STORE_OPS = STORE_WRITE_OPS + STORE_READ_OPS + STORE_LIFECYCLE_OPS
ROUTE_REGISTER = ("add_get", "add_post", "add_put", "add_delete",
                  "add_route")
ROUTE_PREFIXES = ("/rpc/", "/api/v1/")


@dataclass
class Site:
    """One extracted occurrence, enough to mint a Finding."""
    path: str           # repo-relative, posix
    line: int
    col: int
    symbol: str         # enclosing qualname
    detail: str = ""


@dataclass
class KeyUse:
    key: str
    site: Site
    family: bool = False      # key is a prefix (startswith / f-string)


@dataclass
class StoreOp:
    key: str                  # normalized: placeholders -> '*'
    op: str
    site: Site
    has_ttl: bool = False


@dataclass
class EnvRead:
    var: str
    default: str              # unparsed default expr, '<required>' if none
    site: Site


@dataclass
class MetricUse:
    name: str
    method: str               # inc / observe / set_gauge / remove_gauge
    site: Site
    family: bool = False      # name is an f-string prefix
    label_keys: tuple = ()


@dataclass
class RouteUse:
    pattern: str              # normalized: {param} / f-holes -> '*'
    site: Site


@dataclass
class ModuleIndex:
    """Per-file parse products reused by every rule."""
    path: str
    tree: ast.AST
    source: str
    consts: dict = field(default_factory=dict)   # NAME -> str|tuple struct
    consts_lineno: dict = field(default_factory=dict)
    scopes: dict = field(default_factory=dict)   # qualname -> ast node


# -- module indexing ---------------------------------------------------------

def _const_struct(node):
    """Literal str, or (possibly nested) tuple/list of literal strs."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            sub = _const_struct(elt)
            if sub is None:
                return None
            out.append(sub)
        return tuple(out)
    return None


class _Indexer(ast.NodeVisitor):
    def __init__(self, idx: ModuleIndex):
        self.idx = idx
        self.stack: list[str] = []

    def _qual(self, name: str) -> str:
        return ".".join(self.stack + [name])

    def visit_ClassDef(self, node):
        self.idx.scopes[self._qual(node.name)] = node
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _func(self, node):
        self.idx.scopes[self._qual(node.name)] = node
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _func
    visit_AsyncFunctionDef = _func

    def visit_Assign(self, node):
        if not self.stack:                      # module level only
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    struct = _const_struct(node.value)
                    if struct is not None:
                        self.idx.consts[tgt.id] = struct
                        self.idx.consts_lineno[tgt.id] = node.lineno
        self.generic_visit(node)


def index_module(repo_root: str, rel_path: str) -> "ModuleIndex | None":
    full = os.path.join(repo_root, rel_path)
    try:
        with open(full, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel_path)
    except (OSError, SyntaxError):
        return None
    idx = ModuleIndex(path=rel_path.replace(os.sep, "/"), tree=tree,
                      source=source)
    _Indexer(idx).visit(tree)
    return idx


def enclosing_symbols(tree: ast.AST) -> dict:
    """id(node) -> qualname of the enclosing function/class."""
    out: dict = {}

    def walk(node, qual):
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual != "<module>" \
                    else child.name
            out[id(child)] = q
            walk(child, q)
    out[id(tree)] = "<module>"
    walk(tree, "<module>")
    return out


# -- scoped dict-key extraction (WIR001) -------------------------------------

def _matches_var(node, var: str) -> bool:
    if "." in var:                              # e.g. "self._stats"
        head, attr = var.rsplit(".", 1)
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == head)
    return isinstance(node, ast.Name) and node.id == var


def _lit_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _joined_prefix(node):
    """f-string with a leading literal part -> that prefix, else None."""
    if isinstance(node, ast.JoinedStr) and node.values:
        head = _lit_str(node.values[0])
        if head:
            return head
    return None


class _ScopeKeys:
    """Reads and writes of one dict variable inside one scope."""

    def __init__(self, idx: ModuleIndex, scope_node, scope_qual: str,
                 var: str):
        self.idx = idx
        self.node = scope_node
        self.qual = scope_qual
        self.var = var
        self.reads: list[KeyUse] = []
        self.writes: list[KeyUse] = []
        self._aliases: set[str] = set()          # loop vars over the dict
        self._accessors: set[str] = set()        # nested closures over var

    def _site(self, node, detail="") -> Site:
        return Site(self.idx.path, node.lineno, node.col_offset,
                    self.qual, detail)

    def _is_var(self, node) -> bool:
        return _matches_var(node, self.var)

    def run(self):
        self._find_aliases_and_accessors()
        for node in ast.walk(self.node):
            self._collect(node)
        return self

    def _find_aliases_and_accessors(self):
        for node in ast.walk(self.node):
            # for k in var / var.keys() / var.items()  -> k aliases a key
            if isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Attribute) and \
                        it.func.attr in ("keys", "items") and \
                        self._is_var(it.func.value):
                    tgt = node.target
                    if it.func.attr == "items" and \
                            isinstance(tgt, ast.Tuple) and tgt.elts:
                        tgt = tgt.elts[0]
                    if isinstance(tgt, ast.Name):
                        self._aliases.add(tgt.id)
                elif self._is_var(it):
                    if isinstance(node.target, ast.Name):
                        self._aliases.add(node.target.id)
            # nested closure reading var -> literal call args are reads
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not self.node:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Attribute) and \
                            sub.attr in ("get", "pop") and \
                            self._is_var(sub.value):
                        self._accessors.add(node.name)
                        break
                    if isinstance(sub, ast.Subscript) and \
                            self._is_var(sub.value):
                        self._accessors.add(node.name)
                        break

    def _read(self, key, node, family=False, detail=""):
        if family or KEY_RE.match(key):
            self.reads.append(KeyUse(key, self._site(node, detail), family))

    def _write(self, key, node, family=False, detail=""):
        if family or KEY_RE.match(key):
            self.writes.append(KeyUse(key, self._site(node, detail),
                                      family))

    def _collect(self, node):
        # var["k"] loads/stores, var[f"pfx{..}"] family stores
        if isinstance(node, ast.Subscript) and self._is_var(node.value):
            key = _lit_str(node.slice)
            prefix = _joined_prefix(node.slice)
            if isinstance(node.ctx, ast.Store):
                if key is not None:
                    self._write(key, node)
                elif prefix is not None:
                    self._write(prefix, node, family=True)
            elif isinstance(node.ctx, ast.Load) and key is not None:
                self._read(key, node)
            return
        # "k" in var
        if isinstance(node, ast.Compare) and node.comparators and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                self._is_var(node.comparators[0]):
            key = _lit_str(node.left)
            if key is not None:
                self._read(key, node)
            return
        if isinstance(node, ast.Call):
            self._collect_call(node)
            return
        # var = {...} / augmented forms handled via Subscript above
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if self._is_var(tgt) and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        key = _lit_str(k)
                        if key is not None:
                            self._write(key, k)
        # k.startswith("pfx") where k loops over the dict -> family read
        # (producer scopes translate these into family writes in
        #  finish() when the scope also stores dynamic keys)

    def _collect_call(self, node: ast.Call):
        func = node.func
        # var.get("k") / var.pop / var.setdefault
        if isinstance(func, ast.Attribute) and self._is_var(func.value):
            if func.attr in ("get", "pop") and node.args:
                key = _lit_str(node.args[0])
                if key is not None:
                    self._read(key, node)
            elif func.attr == "setdefault" and node.args:
                key = _lit_str(node.args[0])
                if key is not None:
                    self._write(key, node)
            elif func.attr == "update" and node.args and \
                    isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    key = _lit_str(k)
                    if key is not None:
                        self._write(key, k)
            return
        # alias.startswith("pfx") -> family use
        if isinstance(func, ast.Attribute) and \
                func.attr == "startswith" and \
                isinstance(func.value, ast.Name) and \
                func.value.id in self._aliases and node.args:
            arg = node.args[0]
            prefixes = []
            if _lit_str(arg) is not None:
                prefixes = [_lit_str(arg)]
            elif isinstance(arg, (ast.Tuple, ast.List)):
                prefixes = [p for p in map(_lit_str, arg.elts) if p]
            for p in prefixes:
                self._read(p, node, family=True)
            return
        # accessor closure: _f("k")
        if isinstance(func, ast.Name) and func.id in self._accessors \
                and node.args:
            key = _lit_str(node.args[0])
            if key is not None:
                self._read(key, node, detail=f"via {func.id}()")
            return
        # helper taking (var, "k") in any positions: _num(stats, "k")
        if isinstance(func, (ast.Name, ast.Attribute)):
            has_var = any(self._is_var(a) for a in node.args)
            if has_var:
                for a in node.args:
                    key = _lit_str(a)
                    if key is not None and KEY_RE.match(key):
                        self._read(key, a)

    def finish_consumer(self):
        """Consumer-only post-pass: ``for k in ("a", "b"): ... var[k]``
        (or ``k in var`` / ``var.get(k)``) reads every tuple element."""
        for node in ast.walk(self.node):
            if not isinstance(node, ast.For) or \
                    not isinstance(node.iter, (ast.Tuple, ast.List)):
                continue
            tgt = node.target
            if not isinstance(tgt, ast.Name):
                continue
            loop_var = tgt.id

            def _keyed_by_loop(n):
                if isinstance(n, ast.Subscript) and self._is_var(n.value) \
                        and isinstance(n.slice, ast.Name) \
                        and n.slice.id == loop_var:
                    return True
                if isinstance(n, ast.Compare) and \
                        isinstance(n.left, ast.Name) and \
                        n.left.id == loop_var and \
                        any(isinstance(op, (ast.In, ast.NotIn))
                            for op in n.ops) and \
                        n.comparators and self._is_var(n.comparators[0]):
                    return True
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("get", "pop") and \
                        self._is_var(n.func.value) and n.args and \
                        isinstance(n.args[0], ast.Name) and \
                        n.args[0].id == loop_var:
                    return True
                return False

            if not any(_keyed_by_loop(n) for n in ast.walk(node)):
                continue
            for elt in node.iter.elts:
                key = _lit_str(elt)
                if key is not None and KEY_RE.match(key):
                    self._read(key, elt, detail="tuple loop")
        return self

    def finish_producer(self):
        """Producer-only post-pass: forwarded literal tuples and
        startswith-filtered copy loops become writes."""
        for node in ast.walk(self.node):
            if not isinstance(node, ast.For):
                continue
            # loop target name(s): `for k in ...` or `for k, v in ...`
            tgt = node.target
            names = [tgt.id] if isinstance(tgt, ast.Name) else \
                [t.id for t in tgt.elts if isinstance(t, ast.Name)] \
                if isinstance(tgt, ast.Tuple) else []
            if not names:
                continue
            loop_var = names[0]
            stores = any(
                isinstance(n, ast.Subscript) and self._is_var(n.value)
                and isinstance(n.ctx, ast.Store)
                and isinstance(n.slice, ast.Name)
                and n.slice.id == loop_var
                for n in ast.walk(node))
            if not stores:
                continue
            # for k in ("a", "b", ...): ... var[k] = ...
            if isinstance(node.iter, (ast.Tuple, ast.List)):
                for elt in node.iter.elts:
                    key = _lit_str(elt)
                    if key is not None:
                        self._write(key, elt, detail="forwarded tuple")
            # for k, v in <src>.items(): if k.startswith("pfx"): var[k]=v
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "startswith" and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == loop_var and sub.args:
                    arg = sub.args[0]
                    prefixes = [_lit_str(arg)] \
                        if _lit_str(arg) is not None else \
                        [p for p in map(_lit_str, arg.elts) if p] \
                        if isinstance(arg, (ast.Tuple, ast.List)) else []
                    for p in prefixes:
                        self._write(p, sub, family=True,
                                    detail="forwarded family")
        return self


def extract_scope_keys(idx: ModuleIndex, qualname: str, var: str,
                       producer: bool) -> "_ScopeKeys | None":
    node = idx.scopes.get(qualname)
    if node is None:
        return None
    sk = _ScopeKeys(idx, node, qualname, var).run()
    if producer:
        sk.finish_producer()
    else:
        sk.finish_consumer()
    return sk


def extract_const_list(idx: ModuleIndex, name: str) -> list[str]:
    """Flatten a module-level str tuple/list constant (nested pairs ok),
    keeping only dict-key-looking strings (metric names filtered out)."""
    struct = idx.consts.get(name)
    out: list[str] = []

    def flat(s):
        if isinstance(s, str):
            if KEY_RE.match(s) and not s.startswith("tpu9_"):
                out.append(s)
        elif isinstance(s, tuple):
            for e in s:
                flat(e)
    if struct is not None:
        flat(struct)
    return out


# -- metrics (WIR002) --------------------------------------------------------

def _resolve_metric_names(arg, enclosing_fn, idx: ModuleIndex):
    """First arg of a metrics call -> [(name, family?)]; resolves loop
    vars iterating module-level tuples (the health.py gauge-family
    pattern, incl. ``for gauge, key in PAIRS``)."""
    lit = _lit_str(arg)
    if lit is not None:
        return [(lit, False)]
    prefix = _joined_prefix(arg)
    if prefix is not None:
        return [(prefix, True)]
    if isinstance(arg, ast.Name) and enclosing_fn is not None:
        names = []
        for node in ast.walk(enclosing_fn):
            if not isinstance(node, (ast.For, ast.comprehension)):
                continue
            it = node.iter
            const = idx.consts.get(it.id) if isinstance(it, ast.Name) \
                else _const_struct(it)
            if const is None:
                continue
            tgt = node.target
            if isinstance(tgt, ast.Name) and tgt.id == arg.id:
                names += [(e, False) for e in const
                          if isinstance(e, str)]
            elif isinstance(tgt, ast.Tuple):
                for pos, t in enumerate(tgt.elts):
                    if isinstance(t, ast.Name) and t.id == arg.id:
                        names += [(e[pos], False) for e in const
                                  if isinstance(e, tuple)
                                  and len(e) > pos
                                  and isinstance(e[pos], str)]
        return [(n, fam) for n, fam in names if n.startswith("tpu9_")]
    return []


def _label_keys(call: ast.Call, enclosing_fn) -> tuple:
    labels = None
    for kw in call.keywords:
        if kw.arg == "labels":
            labels = kw.value
    if labels is None and len(call.args) >= 3:
        labels = call.args[2]
    if isinstance(labels, ast.Name) and enclosing_fn is not None:
        for node in ast.walk(enclosing_fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict) and \
                    any(isinstance(t, ast.Name) and t.id == labels.id
                        for t in node.targets):
                labels = node.value
    if isinstance(labels, ast.Dict):
        return tuple(k for k in map(_lit_str, labels.keys) if k)
    return ()


def extract_metrics(idx: ModuleIndex) -> list[MetricUse]:
    symbols = enclosing_symbols(idx.tree)
    # map each call to its enclosing function node for name resolution
    fn_of: dict[int, ast.AST] = {}

    def assign_fns(node, fn):
        for child in ast.iter_child_nodes(node):
            f = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            fn_of[id(child)] = f
            assign_fns(child, f)
    assign_fns(idx.tree, None)

    out: list[MetricUse] = []
    for node in ast.walk(idx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_METHODS
                and node.args):
            continue
        recv = node.func.value
        recv_name = recv.id if isinstance(recv, ast.Name) else \
            recv.attr if isinstance(recv, ast.Attribute) else ""
        if "metric" not in recv_name:
            continue
        fn = fn_of.get(id(node))
        for name, family in _resolve_metric_names(node.args[0], fn, idx):
            if not family and not METRIC_RE.match(name):
                continue
            out.append(MetricUse(
                name, node.func.attr,
                Site(idx.path, node.lineno, node.col_offset,
                     symbols.get(id(node), "<module>")),
                family=family,
                label_keys=_label_keys(node, fn)))
    return out


def extract_metric_literals(idx: ModuleIndex) -> list[MetricUse]:
    """Every ``tpu9_*`` string literal in a file (the *asserted* side:
    tests, CLI renderers, docs-in-code). Emission calls are collected
    separately — the checker subtracts them."""
    symbols = enclosing_symbols(idx.tree)
    out = []
    for node in ast.walk(idx.tree):
        lit = _lit_str(node) if isinstance(node, ast.Constant) else None
        if lit and METRIC_RE.match(lit):
            out.append(MetricUse(
                lit, "literal",
                Site(idx.path, node.lineno, node.col_offset,
                     symbols.get(id(node), "<module>"))))
    return out


# -- store keys (KEY001) -----------------------------------------------------

_PLACEHOLDER = re.compile(r"\{[^}]*\}|%s|%d")


def _normalize_key(raw: str) -> str:
    return _PLACEHOLDER.sub("*", raw)


def _resolve_key_arg(arg, idx: ModuleIndex):
    lit = _lit_str(arg)
    if lit is not None:
        return _normalize_key(lit)
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            p = _lit_str(v)
            parts.append(p if p is not None else "*")
        return _normalize_key("".join(parts))
    if isinstance(arg, ast.Name):
        const = idx.consts.get(arg.id)
        if isinstance(const, str):
            return _normalize_key(const)
        return None
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left = _resolve_key_arg(arg.left, idx)
        if left is not None:
            right = _resolve_key_arg(arg.right, idx)
            return left + (right if right is not None else "*")
        return None
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod):
        left = _lit_str(arg.left)
        if left is not None:
            return _normalize_key(left)
        return None
    if isinstance(arg, ast.Call) and \
            isinstance(arg.func, ast.Attribute) and \
            arg.func.attr == "format":
        return _resolve_key_arg(arg.func.value, idx)
    return None


def extract_store_ops(idx: ModuleIndex) -> list[StoreOp]:
    symbols = enclosing_symbols(idx.tree)
    out = []
    for node in ast.walk(idx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in STORE_OPS
                and node.args):
            continue
        recv = node.func.value
        recv_name = recv.id if isinstance(recv, ast.Name) else \
            recv.attr if isinstance(recv, ast.Attribute) else ""
        if "store" not in recv_name:
            continue
        key = _resolve_key_arg(node.args[0], idx)
        if key is None or (":" not in key and "*" not in key):
            continue
        has_ttl = any(kw.arg == "ttl" and
                      not (isinstance(kw.value, ast.Constant)
                           and kw.value.value is None)
                      for kw in node.keywords)
        out.append(StoreOp(key, node.func.attr,
                           Site(idx.path, node.lineno, node.col_offset,
                                symbols.get(id(node), "<module>")),
                           has_ttl=has_ttl))
    return out


# -- env reads (ENV001) ------------------------------------------------------

def _is_environ(node) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "environ"
    return isinstance(node, ast.Name) and node.id == "environ"


def extract_env_reads(idx: ModuleIndex) -> list[EnvRead]:
    symbols = enclosing_symbols(idx.tree)
    # `env.get(...) or X` — the effective default is X, so capture the
    # BoolOp tail for divergence comparison
    or_tail: dict[int, str] = {}
    for node in ast.walk(idx.tree):
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) \
                and len(node.values) >= 2:
            try:
                or_tail[id(node.values[0])] = ast.unparse(node.values[1])
            except Exception:
                pass
    out = []
    for node in ast.walk(idx.tree):
        var = default = None
        if isinstance(node, ast.Call) and node.args:
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "get" \
                    and _is_environ(func.value):
                var = _lit_str(node.args[0])
                default = ast.unparse(node.args[1]) \
                    if len(node.args) > 1 else "<required>"
            elif isinstance(func, ast.Attribute) and \
                    func.attr == "getenv" or \
                    isinstance(func, ast.Name) and func.id == "getenv":
                var = _lit_str(node.args[0])
                default = ast.unparse(node.args[1]) \
                    if len(node.args) > 1 else "<required>"
        elif isinstance(node, ast.Subscript) and \
                _is_environ(node.value) and \
                isinstance(node.ctx, ast.Load):
            var = _lit_str(node.slice)
            default = "<required>"
        if var is None or not var.startswith("TPU9_"):
            continue
        tail = or_tail.get(id(node))
        if tail is not None:
            default = f"{default} or {tail}"
        out.append(EnvRead(var, default,
                           Site(idx.path, node.lineno, node.col_offset,
                                symbols.get(id(node), "<module>"))))
    return out


# -- rpc routes (RPC001) -----------------------------------------------------

def _route_pattern(raw: str) -> str:
    return _PLACEHOLDER.sub("*", raw.split("?")[0])


def extract_routes(idx: ModuleIndex) -> tuple[list[RouteUse],
                                              list[RouteUse]]:
    """(registered, called). Call-site literals are any string containing
    a route prefix outside registration calls and docstrings."""
    symbols = enclosing_symbols(idx.tree)
    registered: list[RouteUse] = []
    called: list[RouteUse] = []
    skip_ids: set[int] = set()

    # docstrings: standalone string expressions
    for node in ast.walk(idx.tree):
        if isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            skip_ids.add(id(node.value))

    for node in ast.walk(idx.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ROUTE_REGISTER:
            arg_i = 1 if node.func.attr == "add_route" else 0
            if len(node.args) > arg_i:
                path = _lit_str(node.args[arg_i])
                if path and path.startswith(ROUTE_PREFIXES):
                    registered.append(RouteUse(
                        _route_pattern(path),
                        Site(idx.path, node.lineno, node.col_offset,
                             symbols.get(id(node), "<module>"))))
                    skip_ids.add(id(node.args[arg_i]))

    for node in ast.walk(idx.tree):
        text = None
        if isinstance(node, ast.Constant) and id(node) not in skip_ids:
            text = _lit_str(node)
        elif isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                p = _lit_str(v)
                parts.append(p if p is not None else "*")
            text = "".join(parts)
        if not text:
            continue
        for prefix in ROUTE_PREFIXES:
            pos = text.find(prefix)
            if pos >= 0:
                called.append(RouteUse(
                    _route_pattern(text[pos:]),
                    Site(idx.path, node.lineno, node.col_offset,
                         symbols.get(id(node), "<module>"))))
                break
    return registered, called


def route_match(reg: str, call: str) -> bool:
    """Segment-wise match where '*' wildcards one segment on either side.

    Asymmetric on the *call* side: string-concat builds
    (``"/rpc/pod/" + name`` → pattern ``/rpc/pod/``) and f-string tails
    (``f"/rpc/pod/{name}"`` → ``/rpc/pod/*``) are prefixes — they match
    any registered route that shares the leading segments, even a longer
    one.  Registered patterns are always full paths and never
    prefix-match."""
    sr = reg.rstrip("/").split("/")
    sc = call.rstrip("/").split("/")
    seg_ok = lambda x, y: x == y or x == "*" or y == "*"
    if sc and sc[-1].endswith("*"):
        # f-string tail: the last call segment is open-ended.  ``machine*``
        # (query string in the variable) needs the stem to prefix the
        # registered segment; ``**`` (path tail in the variable) matches
        # any suffix.
        if len(sr) < len(sc):
            return False
        if not all(seg_ok(x, y) for x, y in zip(sr[:len(sc) - 1], sc[:-1])):
            return False
        stem = sc[-1].rstrip("*")
        last = sr[len(sc) - 1]
        return last == "*" or last.startswith(stem)
    if call.endswith("/"):
        # string-concat build: the call literal stops at a separator
        if len(sc) > len(sr):
            return False
        sr = sr[:len(sc)]
    elif len(sr) != len(sc):
        return False
    return all(seg_ok(x, y) for x, y in zip(sr, sc))
