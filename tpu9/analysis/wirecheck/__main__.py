"""CLI entry: ``python -m tpu9.analysis.wirecheck``.

Exit codes mirror tpu9lint: 0 clean (or everything known/suppressed),
1 new findings, 2 contract/parse errors. Warn-tier findings (dead
telemetry, unasserted metrics) report but never gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..findings import JSON_SCHEMA_VERSION, finding_json, load_baseline
from ..runner import find_repo_root
from . import (DEFAULT_BASELINE, DEFAULT_CONTRACTS, WIRE_RULES,
               run_wirecheck)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu9.analysis.wirecheck",
        description="wirecheck: static contract verification of the "
                    "string-keyed wire surfaces (heartbeat fields, "
                    "tpu9_* metrics, store keys, TPU9_* env, rpc routes)")
    ap.add_argument("roots", nargs="*", default=None,
                    help="report findings only under these paths "
                         "(extraction always sees the whole repo)")
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--contracts", default=DEFAULT_CONTRACTS,
                    help="contracts toml (default: %(default)s)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="triaged baseline json (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format; json emits the stable schema "
                         "shared with tpu9lint/graphcheck")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-known", action="store_true",
                    help="also print baselined findings")
    ap.add_argument("--no-warn", action="store_true",
                    help="hide warn-tier findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in WIRE_RULES.items():
            print(f"{rid}  {desc}")
        return 0

    repo_root = args.repo_root or find_repo_root()
    select = ({r.strip() for r in args.select.split(",") if r.strip()}
              or None)
    contracts = args.contracts
    if not os.path.isabs(contracts):
        contracts = os.path.join(repo_root, contracts)
    result = run_wirecheck(repo_root, roots=args.roots or None,
                           select=select, contracts_path=contracts)

    if args.no_baseline:
        new, known, stale = result.findings, [], []
    else:
        bl_path = args.baseline
        if bl_path and not os.path.isabs(bl_path):
            bl_path = os.path.join(repo_root, bl_path)
        baseline = load_baseline(bl_path)
        new, known, stale = baseline.split(result.findings)
        if args.roots:
            stale = [e for e in stale
                     if any(e.get("path", "") == r.rstrip("/")
                            or e.get("path", "").startswith(
                                r.rstrip("/") + "/")
                            for r in args.roots)]
        if select:
            stale = [e for e in stale if e.get("rule") in select]

    if args.format == "json":
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "tool": "wirecheck",
            "files_scanned": result.files_scanned,
            "elapsed_s": round(result.elapsed_s, 3),
            "findings": [finding_json(f, "new") for f in new]
            + [finding_json(f, "baselined") for f in known]
            + ([] if args.no_warn
               else [finding_json(w, "warn") for w in result.warnings]),
            "stale": [e["fingerprint"] for e in stale],
            "suppressed_inline": len(result.suppressed),
            "parse_errors": result.parse_errors,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if args.show_known:
            for f in known:
                print(f"known    {f.format()}")
        if not args.no_warn:
            for w in result.warnings:
                print(f"warn     {w.format()}")
        for e in stale:
            print(f"stale baseline entry (finding no longer fires — prune "
                  f"it): {e['rule']} {e['path']} [{e.get('symbol')}] "
                  f"{e['fingerprint']}")
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
        counts = ", ".join(f"{r}={n}" for r, n in sorted(
            result.by_rule().items()))
        print(f"wirecheck: {result.files_scanned} files in "
              f"{result.elapsed_s:.2f}s — {len(new)} new, {len(known)} "
              f"baselined, {len(result.warnings)} warn, "
              f"{len(result.suppressed)} noqa'd"
              + (f" ({counts})" if counts else ""))

    if result.parse_errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
