"""tpu9 wirecheck — static contract verification of the string-keyed wire
surfaces (ISSUE 18).

The fleet's control plane speaks in untyped string-keyed dicts:
``engine.stats()`` → pressure-heartbeat extras → fleetobs/watchdog/
goodput/scaleout consumers → ``/api/v1/metrics`` → ``tpu9 top``, plus
store key namespaces, ``TPU9_*`` env knobs, ``tpu9_*`` metric names and
``/rpc/*`` routes. Every producer/consumer pair on those surfaces is a
silent-drift hazard: a renamed field fails no test, it just reads 0.0
forever. wirecheck AST-extracts both sides of each surface and asserts
agreement against the declarative ``tpu9/analysis/contracts.toml``.

Same machinery as tpu9lint (PR 7): the shared Finding schema, inline
``# tpu9: noqa[RULE] reason`` suppressions, and a triaged baseline at
``scripts/wire_baseline.json``. Gate entry: ``scripts/wire_gate.py``;
CLI: ``python -m tpu9.analysis.wirecheck``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from ..findings import (Finding, apply_suppressions, assign_occurrences,
                        parse_suppressions)
from . import checks as _checks
from .checks import ALL_CHECKS, WireContracts

DEFAULT_CONTRACTS = "tpu9/analysis/contracts.toml"
DEFAULT_BASELINE = "scripts/wire_baseline.json"
DEFAULT_ROOTS = ("tpu9", "scripts", "examples", "tests", "bench.py")

WIRE_RULES = {
    "WIR001": "stats/heartbeat field consumed-but-never-produced (and "
              "produced-but-never-consumed dead telemetry, warn tier)",
    "WIR002": "tpu9_* metric asserted-vs-emitted drift; per-replica "
              "gauges without remove_gauge coverage",
    "KEY001": "store key namespace undeclared / cross-plane write / "
              "non-atomic multi-writer op / missing TTL discipline",
    "ENV001": "TPU9_* env read outside tpu9/config.py or its declared "
              "reader; divergent inline defaults",
    "RPC001": "registered route without caller / call without handler; "
              "bench_guard HARD_FIELDS bench.py cannot emit",
}


@dataclass
class WirecheckResult:
    findings: list = field(default_factory=list)
    warnings: list = field(default_factory=list)
    suppressed: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)
    files_scanned: int = 0
    elapsed_s: float = 0.0

    def by_rule(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out


def _iter_files(repo_root: str, roots) -> list[str]:
    out = []
    for root in roots:
        full = os.path.join(repo_root, root)
        if os.path.isfile(full) and root.endswith(".py"):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name),
                                          repo_root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def run_wirecheck(repo_root: str, roots=None, select=None,
                  contracts_path: str = None) -> WirecheckResult:
    """Full-repo wire scan. ``roots``/``select`` only *filter* the
    reported findings (surface agreement is inherently cross-file, so
    extraction always sees the whole repo); the gate preserves
    out-of-scope baseline entries the same way tpu9lint does."""
    t0 = time.monotonic()
    res = WirecheckResult()
    cpath = contracts_path or os.path.join(repo_root, DEFAULT_CONTRACTS)
    try:
        contracts = WireContracts.load(cpath)
    except (OSError, ValueError) as exc:
        res.parse_errors.append(f"{DEFAULT_CONTRACTS}: {exc}")
        res.elapsed_s = time.monotonic() - t0
        return res

    ctx = _checks.CheckContext(repo_root, contracts,
                               contracts_path=cpath)
    files = _iter_files(repo_root, DEFAULT_ROOTS)
    ctx.scan(files)
    res.files_scanned = len(files)
    res.parse_errors.extend(ctx.parse_errors)

    findings: list[Finding] = []
    warnings: list[Finding] = []
    for rule, check in ALL_CHECKS.items():
        if select and rule not in select:
            continue
        f, w = check(ctx)
        findings += f
        warnings += w

    # inline noqa suppressions, file by file (shared tpu9lint semantics);
    # contract-side findings anchor to contracts.toml, which has no
    # Python comments — those are baseline-only
    by_path: dict[str, list[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    kept_all: list[Finding] = []
    for path, fs in by_path.items():
        if not path.endswith(".py"):
            kept_all.extend(fs)
            continue
        full = os.path.join(repo_root, path)
        try:
            with open(full, encoding="utf-8") as fh:
                sups = parse_suppressions(fh.read())
        except OSError:
            kept_all.extend(fs)
            continue
        kept, suppressed = apply_suppressions(fs, sups, path)
        # SUP001 minting is tpu9lint's job over the whole tree — only
        # keep wire-rule findings and their suppressions here
        kept_all.extend(f for f in kept if f.rule != "SUP001")
        res.suppressed.extend(suppressed)
    # warnings honour noqa too, without minting SUP001
    warn_by_path: dict[str, list[Finding]] = {}
    for w in warnings:
        warn_by_path.setdefault(w.path, []).append(w)
    kept_warns: list[Finding] = []
    for path, ws in warn_by_path.items():
        full = os.path.join(repo_root, path)
        try:
            with open(full, encoding="utf-8") as fh:
                sups = parse_suppressions(fh.read())
        except OSError:
            kept_warns.extend(ws)
            continue
        kept, suppressed = apply_suppressions(ws, sups, path)
        kept_warns.extend(w for w in kept if w.rule != "SUP001")
        res.suppressed.extend(suppressed)

    if roots:
        def _in(f):
            return any(f.path == r or f.path.startswith(r.rstrip("/") + "/")
                       for r in roots)
        kept_all = [f for f in kept_all if _in(f)]
        kept_warns = [w for w in kept_warns if _in(w)]

    res.findings = assign_occurrences(kept_all)
    res.warnings = assign_occurrences(kept_warns)
    res.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    res.warnings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    res.elapsed_s = time.monotonic() - t0
    return res
