"""tpu9lint — project-native static analysis for the bug classes this repo
has shipped: swallowed async cancellation, fire-and-forget tasks, blocking
calls on the event loop, host-device syncs on the serve hot path, jit
recompile hazards, and import-boundary violations.

Run it:

    python -m tpu9.analysis                 # gate mode: repo + baseline
    python -m tpu9.analysis --list-rules
    python -m tpu9.analysis path/to/file.py --no-baseline
    python -m tpu9.analysis --format json   # stable CI schema

The sharding/dtype/donation invariants of the traced serving graphs have
their own verifier, ``python -m tpu9.analysis.graphcheck`` (ISSUE 11):
Pass A lowers every serving graph per preset × topology and checks the
jaxpr/compiled artifact; Pass B contributes the SHD001/SHD002/DTY001
rules that run here too.

Suppress a reviewed false positive inline (the reason is mandatory):

    loop.create_task(pump())  # tpu9: noqa[ASY002] handle owned by caller

or record it in scripts/lint_baseline.json via scripts/lint_gate.py
--update-baseline --reason "...". The gate fails on any NEW finding.
"""

from .findings import Baseline, Finding, load_baseline
from .runner import (ALL_RULES, DEFAULT_BASELINE, DEFAULT_ROOTS,
                     AnalysisResult, find_repo_root, run_analysis, run_gate)

__all__ = ["ALL_RULES", "DEFAULT_BASELINE", "DEFAULT_ROOTS",
           "AnalysisResult", "Baseline", "Finding", "find_repo_root",
           "load_baseline", "run_analysis", "run_gate"]
