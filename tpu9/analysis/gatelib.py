"""Shared ratchet-gate engine (ISSUE 18).

Every ``scripts/*_gate.py`` used to carry its own copy of the same
semantics: run the tool, split findings against a triaged baseline,
filter staleness to the scanned scope, update the baseline without
destroying out-of-scope triage, ratchet with ``--strict-stale``. One
drifting copy per gate is exactly the bug class this package exists to
kill, so the semantics live here once and the gates are thin wrappers.

A tool plugs in as a callable ``run(repo_root, roots, select, args) ->
result`` where the result carries ``findings`` / ``suppressed`` /
``parse_errors`` / ``files_scanned`` / ``elapsed_s`` (both
``AnalysisResult`` and ``WirecheckResult`` do).
"""

from __future__ import annotations

import argparse
import os
import sys

from .findings import Baseline, load_baseline
from .runner import find_repo_root


def in_roots(path: str, roots) -> bool:
    for r in roots:
        r = r.rstrip("/")
        if path == r or path.startswith(r + "/"):
            return True
    return False


def build_parser(name: str, doc: str, baseline_default: str,
                 budget_s: float = 0.0) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=doc)
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--roots", nargs="*", default=None)
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=baseline_default)
    ap.add_argument("--strict-stale", action="store_true",
                    help="fail when baseline entries no longer fire")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record every NEW finding as suppressed (requires "
                         "--reason) and prune stale entries")
    ap.add_argument("--reason", default="",
                    help="mandatory triage reason for --update-baseline")
    if budget_s:
        ap.add_argument("--budget-s", type=float, default=budget_s,
                        help="fail when a full-repo run exceeds this wall "
                             "clock (0 disables; default %(default)s)")
    return ap


def ratchet_main(name: str, run, baseline_default: str, argv=None,
                 doc: str = "", budget_s: float = 0.0, add_args=None) -> int:
    ap = build_parser(name, doc or f"{name}: baseline ratchet gate",
                      baseline_default, budget_s=budget_s)
    if add_args:
        add_args(ap)
    args = ap.parse_args(argv)

    repo_root = args.repo_root or find_repo_root()
    select = ({r.strip() for r in args.select.split(",") if r.strip()}
              or None)
    # a run over non-default roots (or a rule subset) sees only a slice
    # of the repo: baseline entries outside the slice would look "stale"
    # and must not be pruned or even reported as such
    scoped = bool(args.roots) or bool(select)
    result = run(repo_root, args.roots or None, select, args)

    bl_path = args.baseline
    if not os.path.isabs(bl_path):
        bl_path = os.path.join(repo_root, bl_path)
    baseline = load_baseline(bl_path)
    new, known, stale = baseline.split(result.findings)
    if args.roots:
        stale = [e for e in stale
                 if in_roots(e.get("path", ""), args.roots)]
    if select:
        stale = [e for e in stale if e.get("rule") in select]

    for err in result.parse_errors:
        print(f"{name}: parse error: {err}", file=sys.stderr)
    if result.parse_errors:
        return 2

    if args.update_baseline:
        if new and not args.reason.strip():
            print(f"{name}: --update-baseline needs --reason (suppressions "
                  "without a reason are not triage)", file=sys.stderr)
            return 2
        fresh = Baseline()
        fresh.fixed = baseline.fixed
        for f in known:
            fresh.entries[f.fingerprint] = baseline.entries[f.fingerprint]
        if scoped:
            # keep everything the narrowed run could not see — a scoped
            # update must never destroy the rest of the triage ledger
            # (in-scope stale entries are still pruned)
            live = {f.fingerprint for f in known}
            for fp, e in baseline.entries.items():
                unseen = (args.roots
                          and not in_roots(e.get("path", ""), args.roots)) \
                    or (select and e.get("rule") not in select)
                if fp not in live and unseen:
                    fresh.entries[fp] = e
        for f in new:
            fresh.add(f, args.reason.strip())
        fresh.save(bl_path)
        print(f"{name}: baseline updated — {len(new)} added, "
              f"{len(stale)} stale pruned, {len(known)} kept"
              + (" (scoped run: out-of-scope entries preserved)"
                 if scoped else ""))
        return 0

    for f in new:
        print(f"NEW  {f.format()}")
    for w in getattr(result, "warnings", []):
        print(f"warn {w.format()}")
    for e in stale:
        print(f"stale baseline entry (prune or --update-baseline): "
              f"{e['rule']} {e['path']} [{e.get('symbol')}]")
    print(f"{name}: {result.files_scanned} files in "
          f"{result.elapsed_s:.2f}s — {len(new)} new, {len(known)} "
          f"baselined, {len(result.suppressed)} noqa'd, {len(stale)} stale")
    if new:
        print(f"{name}: FAIL — new findings above. Fix them, or suppress "
              "with `# tpu9: noqa[RULE] reason` / --update-baseline "
              "--reason.", file=sys.stderr)
        return 1
    if stale and args.strict_stale:
        print(f"{name}: FAIL — stale baseline entries (--strict-stale)",
              file=sys.stderr)
        return 1
    budget = getattr(args, "budget_s", 0.0)
    if budget and not scoped and result.elapsed_s > budget:
        print(f"{name}: FAIL — full run took {result.elapsed_s:.1f}s > "
              f"budget {budget:.0f}s", file=sys.stderr)
        return 1
    print(f"{name}: OK")
    return 0
