"""tpu9lint driver: walk the tree, run every checker, apply suppressions
and the triaged baseline, and report.

Designed to be cheap enough for tier-1: one AST parse per file, every
per-file rule in a single visitor pass, and the two whole-program passes
(JAX001 hot path, BND001 boundaries) reuse the same parsed trees.
"""

from __future__ import annotations

import ast
import os
import time
from dataclasses import dataclass, field
from typing import Optional

from . import boundaries as bnd
from . import rules
from .findings import (Baseline, Finding, apply_suppressions,
                       assign_occurrences, load_baseline, parse_suppressions)

DEFAULT_ROOTS = ("tpu9", "scripts", "examples", "bench.py")
DEFAULT_BASELINE = os.path.join("scripts", "lint_baseline.json")
BOUNDARIES_TOML = os.path.join(os.path.dirname(__file__), "boundaries.toml")

ALL_RULES = {
    "ASY001": "asyncio.wait_for wrapping a cancellable .get()/.wait()",
    "ASY002": "fire-and-forget create_task/ensure_future (weak-ref'd task)",
    "ASY003": "BaseException/bare except in a coroutine without re-raise",
    "ASY004": "blocking call (sleep/subprocess/socket/file IO) in async def",
    "JAX001": "host-device sync reachable from the engine serve loop",
    "JAX002": "jit recompile hazard (inline jit call / jit built in a loop)",
    "OBS001": "wall-clock (time.time) arithmetic for a duration/deadline "
              "in serving/router/worker hot-path files",
    "OBS002": "unbounded metric-label cardinality (request/trace/prompt "
              "ids as metrics.inc/observe/set_gauge label values)",
    "TMO001": "network-facing await without a timeout/deadline in "
              "gateway/router/runner/worker/cache/statestore hot paths",
    "BND001": "import-boundary contract violation (boundaries.toml)",
    "SHD001": "jax.jit opened outside the GraphFactory in mesh-capable "
              "serving modules (no explicit out_shardings)",
    "SHD002": "donated buffer read after the donating jit call",
    "DTY001": "raw int8 KV symbol imported outside the declared carrier "
              "modules (boundaries.toml [graphcheck])",
    "SUP001": "noqa suppression without a mandatory reason",
}


def find_repo_root(start: Optional[str] = None) -> str:
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            # fall back to the package's grandparent (repo checkout layout)
            return os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        d = parent


@dataclass
class AnalysisResult:
    findings: list[Finding] = field(default_factory=list)   # post-noqa
    suppressed: list[Finding] = field(default_factory=list)  # inline noqa'd
    parse_errors: list[str] = field(default_factory=list)
    files_scanned: int = 0
    elapsed_s: float = 0.0

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_py_files(repo_root: str, roots) -> list[str]:
    out = []
    for root in roots:
        abs_root = os.path.join(repo_root, root)
        if os.path.isfile(abs_root):
            if abs_root.endswith(".py"):
                out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def run_analysis(repo_root: Optional[str] = None,
                 roots=DEFAULT_ROOTS,
                 select: Optional[set[str]] = None,
                 boundaries_toml: Optional[str] = None) -> AnalysisResult:
    t0 = time.perf_counter()
    repo_root = repo_root or find_repo_root()
    result = AnalysisResult()

    trees: dict[str, ast.AST] = {}
    sources: dict[str, str] = {}
    for rel in iter_py_files(repo_root, roots):
        try:
            with open(os.path.join(repo_root, rel), encoding="utf-8") as f:
                src = f.read()
            trees[rel] = ast.parse(src, filename=rel)
            sources[rel] = src
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            result.parse_errors.append(f"{rel}: {exc}")
    result.files_scanned = len(trees)

    cfg_path = boundaries_toml or BOUNDARIES_TOML
    cfg = (bnd.BoundaryConfig.load(cfg_path)
           if os.path.exists(cfg_path) else bnd.BoundaryConfig())

    from .graphcheck.astrules import GraphLintConfig, check_graph_file
    gcfg = GraphLintConfig.from_dict(cfg.graph)
    raw: list[Finding] = []
    for rel, tree in trees.items():
        raw.extend(rules.check_file(rel, tree))
        raw.extend(check_graph_file(rel, tree, gcfg))

    raw.extend(bnd.check_boundaries(trees, cfg))

    hot = {rel: tree for rel, tree in trees.items()
           if rel in set(cfg.jax_hotpath_files)}
    if hot and cfg.jax_roots:
        raw.extend(rules.check_jax_hotpath(hot, cfg.jax_roots))

    if select:
        raw = [f for f in raw if f.rule in select]

    # inline suppressions, then stable occurrence numbering.
    # (select is re-applied below: apply_suppressions can mint SUP001) Every scanned
    # file is parsed for noqa — not just files with findings — so a
    # reason-less (or dead) suppression in an otherwise-clean file still
    # raises SUP001 instead of rotting invisibly.
    by_path: dict[str, list[Finding]] = {}
    for f in raw:
        by_path.setdefault(f.path, []).append(f)
    for rel in sorted(sources):
        sups = parse_suppressions(sources[rel])
        if not sups and rel not in by_path:
            continue
        kept, suppressed = apply_suppressions(by_path.get(rel, []), sups,
                                              rel)
        result.findings.extend(kept)
        result.suppressed.extend(suppressed)
    if select:
        result.findings = [f for f in result.findings if f.rule in select]
    assign_occurrences(result.findings)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.elapsed_s = time.perf_counter() - t0
    return result


def gate(result: AnalysisResult, baseline: Baseline
         ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Split post-noqa findings against the baseline: (new, known, stale)."""
    return baseline.split(result.findings)


def run_gate(repo_root: Optional[str] = None,
             roots=DEFAULT_ROOTS,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             boundaries_toml: Optional[str] = None):
    repo_root = repo_root or find_repo_root()
    result = run_analysis(repo_root, roots, boundaries_toml=boundaries_toml)
    bl_path = (os.path.join(repo_root, baseline_path)
               if baseline_path and not os.path.isabs(baseline_path)
               else baseline_path)
    baseline = load_baseline(bl_path)
    new, known, stale = gate(result, baseline)
    return result, new, known, stale
