"""Minimal TOML-subset reader for py3.10 (no stdlib tomllib, and the image
must not grow deps). Supports exactly what boundaries.toml uses: ``[table]``
/ ``[table.sub]`` headers, quoted or bare keys, string values, and arrays of
strings (single-line or multi-line). On 3.11+ the real tomllib is used, so
this stays a fallback, not a dialect.
"""

from __future__ import annotations

import re

_HEADER = re.compile(r"^\[(?P<name>[^\]]+)\]\s*$")
_KEY = re.compile(r'^(?:"(?P<qkey>[^"]+)"|(?P<key>[A-Za-z0-9_.-]+))\s*=\s*'
                  r'(?P<rest>.*)$')
_STR = re.compile(r'"((?:[^"\\]|\\.)*)"')


def loads(text: str) -> dict:
    try:
        import tomllib  # py3.11+
        return tomllib.loads(text)
    except ModuleNotFoundError:
        pass
    root: dict = {}
    table = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i])
        i += 1
        if not line:
            continue
        m = _HEADER.match(line)
        if m:
            table = root
            for part in _split_header(m.group("name")):
                table = table.setdefault(part, {})
            continue
        m = _KEY.match(line)
        if not m:
            raise ValueError(f"tomlmini: cannot parse line: {line!r}")
        key = m.group("qkey") or m.group("key")
        rest = m.group("rest").strip()
        # multi-line array: keep consuming until the bracket closes
        while rest.startswith("[") and not _array_closed(rest):
            if i >= len(lines):
                raise ValueError(f"tomlmini: unterminated array for {key!r}")
            rest += " " + _strip_comment(lines[i])
            i += 1
        table[key] = _value(rest.strip())
    return root


def _split_header(name: str) -> list[str]:
    parts, buf, inq = [], "", False
    for ch in name:
        if ch == '"':
            inq = not inq
        elif ch == "." and not inq:
            parts.append(buf)
            buf = ""
        else:
            buf += ch
    parts.append(buf)
    return [p.strip() for p in parts]


def _strip_comment(line: str) -> str:
    out, inq = "", False
    for ch in line:
        if ch == '"':
            inq = not inq
        if ch == "#" and not inq:
            break
        out += ch
    return out.strip()


def _array_closed(rest: str) -> bool:
    depth, inq = 0, False
    for ch in rest:
        if ch == '"':
            inq = not inq
        elif not inq and ch == "[":
            depth += 1
        elif not inq and ch == "]":
            depth -= 1
    return depth == 0


def _value(rest: str):
    if rest.startswith("["):
        return [_unescape(m) for m in _STR.findall(rest)]
    m = _STR.fullmatch(rest)
    if m:
        return _unescape(m.group(1))
    if rest in ("true", "false"):
        return rest == "true"
    try:
        return int(rest)
    except ValueError:
        raise ValueError(f"tomlmini: unsupported value: {rest!r}")


def _unescape(s: str) -> str:
    return s.replace('\\"', '"').replace("\\\\", "\\")


def load_file(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return loads(f.read())
