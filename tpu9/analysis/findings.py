"""Finding model, inline suppressions, and the triaged baseline.

A finding's identity (``fingerprint``) is deliberately line-number
independent — ``rule | path | enclosing symbol | k-th occurrence`` — so the
baseline survives unrelated edits above the flagged site. Moving a flagged
call to a different function (or adding a second occurrence in the same
function) changes identity and re-surfaces it as NEW, which is the point:
the gate is a ratchet, not a mute button.

Inline suppressions are ``# tpu9: noqa[RULE] reason`` (comma-separated rule
ids allowed) on the flagged line or the line directly above it. The reason
is mandatory: a bare noqa does not suppress — it raises SUP001 instead, so
silencing a checker always leaves a reviewable sentence behind.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

NOQA_RE = re.compile(
    r"#\s*tpu9:\s*noqa\[(?P<rules>[A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)\]"
    r"\s*(?P<reason>.*?)\s*$")


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, posix separators
    line: int            # 1-based
    col: int
    message: str
    symbol: str = "<module>"   # enclosing function/class qualname
    occurrence: int = 0        # k-th finding of (rule, path, symbol)

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.symbol}|{self.occurrence}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.symbol}] {self.message}")

    def to_dict(self) -> dict:
        return {"fingerprint": self.fingerprint, "rule": self.rule,
                "path": self.path, "line": self.line, "symbol": self.symbol,
                "message": self.message}


# The machine-readable finding schema (ISSUE 11): ONE shape for tpu9lint
# and graphcheck findings so CI log consumers parse a single format.
# Schema version bumps are a reviewed change here; adding keys is
# backward-compatible, renaming/removing is not.
JSON_SCHEMA_VERSION = 1
JSON_FIELDS = ("file", "line", "col", "rule", "symbol", "occurrence",
               "message", "fingerprint", "status")


def finding_json(f: "Finding", status: str = "new") -> dict:
    """The stable ``--format json`` record for one finding. ``status`` is
    ``new`` (gate-failing), ``baselined`` (triaged debt) or ``graph``
    (Pass A — not file-anchored, so line/col are 0 and ``file`` is the
    ``graph://cell`` pseudo-path)."""
    return {"file": f.path, "line": f.line, "col": f.col, "rule": f.rule,
            "symbol": f.symbol, "occurrence": f.occurrence,
            "message": f.message, "fingerprint": f.fingerprint,
            "status": status}


def finding_from_json(d: dict) -> "Finding":
    """Inverse of :func:`finding_json` (round-trip tested): rebuilds a
    Finding whose computed fingerprint matches the serialized one."""
    return Finding(d["rule"], d["file"], d["line"], d["col"],
                   d["message"], symbol=d.get("symbol", "<module>"),
                   occurrence=d.get("occurrence", 0))


def assign_occurrences(findings: list[Finding]) -> list[Finding]:
    """Number findings within each (rule, path, symbol) group in source
    order so identical sites in one function get distinct fingerprints."""
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.symbol)
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1
    return findings


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: str
    comment_only: bool = False   # whole line is the comment (covers below)


def parse_suppressions(source: str) -> list[Suppression]:
    out = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = NOQA_RE.search(text)
        if m:
            rules = tuple(r.strip() for r in m.group("rules").split(","))
            out.append(Suppression(i, rules, m.group("reason").strip(),
                                   comment_only=text.lstrip().startswith("#")))
    return out


def apply_suppressions(findings: list[Finding],
                       sups: list[Suppression],
                       path: str) -> tuple[list[Finding], list[Finding]]:
    """Return (kept, suppressed). An end-of-line suppression covers exactly
    its own line; a comment-only line covers the line below (comment-above
    style) — never both, so a new finding on the next line cannot ride an
    adjacent suppression. Reason-less suppressions suppress nothing and add
    a SUP001 finding."""
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    by_line: dict[int, list[Suppression]] = {}
    for s in sups:
        if not s.reason:
            kept.append(Finding(
                "SUP001", path, s.line, 0,
                "suppression without a reason — `# tpu9: noqa[RULE] why` "
                "(the reason is mandatory; bare noqa does not suppress)",
                symbol="<noqa>"))
            continue
        by_line.setdefault(s.line + 1 if s.comment_only else s.line,
                           []).append(s)
    for f in findings:
        matched = any(f.rule in s.rules for s in by_line.get(f.line, []))
        (suppressed if matched else kept).append(f)
    return kept, suppressed


# -- baseline ----------------------------------------------------------------

@dataclass
class Baseline:
    """scripts/lint_baseline.json — the triaged debt ledger.

    ``suppressed`` entries match live findings by fingerprint and carry a
    mandatory reason; ``fixed`` entries are historical record only (the
    triage that removed a finding) and match nothing.
    """
    entries: dict[str, dict] = field(default_factory=dict)  # fp -> entry
    fixed: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            raw = json.load(f)
        bl = cls()
        for e in raw.get("findings", []):
            if e.get("status") == "fixed":
                bl.fixed.append(e)
                continue
            if not e.get("reason", "").strip():
                raise ValueError(
                    f"baseline entry {e.get('fingerprint')} "
                    f"({e.get('rule')} {e.get('path')}) has no reason — "
                    "triaged suppressions must say why")
            bl.entries[e["fingerprint"]] = e
        return bl

    def save(self, path: str) -> None:
        findings = sorted(self.entries.values(),
                          key=lambda e: (e["path"], e["rule"],
                                         e["fingerprint"]))
        findings += self.fixed
        with open(path, "w") as f:
            json.dump({"version": 1, "findings": findings}, f, indent=1,
                      sort_keys=False)
            f.write("\n")

    def split(self, findings: Iterable[Finding]
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """(new, baselined, stale-entries)."""
        live = {f.fingerprint: f for f in findings}
        new = [f for fp, f in live.items() if fp not in self.entries]
        old = [f for fp, f in live.items() if fp in self.entries]
        stale = [e for fp, e in self.entries.items() if fp not in live]
        new.sort(key=lambda f: (f.path, f.line))
        return new, old, stale

    def add(self, finding: Finding, reason: str,
            status: str = "suppressed") -> None:
        e = finding.to_dict()
        e["status"] = status
        e["reason"] = reason
        if status == "fixed":
            self.fixed.append(e)
        else:
            self.entries[finding.fingerprint] = e


def load_baseline(path: Optional[str]) -> Baseline:
    if not path:
        return Baseline()
    try:
        return Baseline.load(path)
    except FileNotFoundError:
        return Baseline()
