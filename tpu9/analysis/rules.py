"""AST checkers for the bug classes this repo has actually shipped.

Per-file rules (class ``FileChecker``):

- **ASY001** ``asyncio.wait_for`` wrapping a cancellable ``.get()`` /
  ``.wait()``. On py3.10, ``wait_for`` can swallow a cancel that races the
  inner future's completion — the Dispatcher ``_exit_loop`` hang PR 1
  diagnosed. Use a bare ``await``, or ``tpu9.utils.aio.queue_get`` /
  ``event_wait`` (``asyncio.wait`` based — it never eats an outer cancel).
- **ASY002** fire-and-forget ``create_task`` / ``ensure_future`` whose
  result is discarded: the event loop holds only a weak reference, so GC
  can collect a *running* task mid-flight. Use ``tpu9.utils.aio.spawn``
  (module task-set + done-callback discard) or store the task.
- **ASY003** a handler in a coroutine that catches ``BaseException`` /
  everything / ``CancelledError`` and never re-raises: cancellation is
  silently converted into "keep running", which is how shutdowns hang.
- **ASY004** blocking calls (``time.sleep``, sync subprocess/socket/file
  IO) directly in an ``async def`` body: stalls every request sharing the
  loop. Wrap in ``asyncio.to_thread`` or use the async equivalent.
- **JAX002** jit recompile hazards: ``jax.jit(f)(x)`` immediately invoked
  (retraces every call) and ``jax.jit``/``pallas_call`` constructed inside
  a loop body instead of cached at module/object scope.
- **OBS001** wall-clock arithmetic in serving/router/worker/cache/runner/
  observability files: ``time.time()`` (directly, or a name/attribute
  assigned from it) used in +/-/comparison — i.e. as a duration or a
  deadline. Under an NTP step those go negative or fire early/late (the
  trace.py durationMs bug, ISSUE 8); durations and deadlines must use
  ``time.monotonic()``. ``time.time()`` stays legal as a wall ANCHOR
  (stored, displayed, or multiplied into epoch nanos) — the two
  legitimate wall-arithmetic sites (anchor + monotonic-duration
  reconstruction, calendar bucket keys) carry reviewed suppressions.
- **OBS002** unbounded metric-label cardinality: a
  ``metrics.inc/observe/set_gauge`` call whose ``labels`` value derives
  from a request id, trace/span id, prompt or task id. Every distinct
  label value mints a PERMANENT series in the registry (counters,
  gauges, and a 2048-slot reservoir per summary) — id-valued labels grow
  it without bound and blow up the Prometheus exposition. Bounded
  dimensions (stub, tenant, phase, reason, worker) are fine;
  per-request identity belongs in span attributes or flight records.

- **TMO001** network-facing awaits without a timeout/deadline in the
  gateway/router/runner/worker/cache/statestore planes (ISSUE 15): an
  awaited HTTP client call (``session.request/get/post/...``,
  ``ws_connect``) with no ``timeout=`` argument, a blocking statestore
  read (``blpop``/``xread``) with no timeout, or a direct
  ``asyncio.open_connection`` await. A hung peer then parks the caller
  forever — the gray-failure shape the health plane can detect but
  never unwedge. Bound the call (``timeout=``/``ClientTimeout``) or
  wrap it in ``asyncio.wait_for``/``aio.cancellable_wait``.

Whole-program rule (``check_jax_hotpath``):

- **JAX001** host-device sync (``.item()``, ``block_until_ready``,
  ``jax.device_get``, ``np.asarray``/``np.array`` on device values) in
  functions reachable from the engine serve loop. Reachability is a
  name-linked call-graph BFS over the hot-path files declared in
  ``boundaries.toml`` — over-approximate on purpose: a false positive
  costs one reviewed suppression, a missed sync costs tokens/sec.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .findings import Finding

ASYNC_RULES = ("ASY001", "ASY002", "ASY003", "ASY004")
JAX_RULES = ("JAX001", "JAX002")

# OBS001 scope: the planes where a stepped wall clock corrupts durations
# that feed admission/routing/latency evidence. The gateway's paid-request
# deadlines are store-persisted epochs (wall by design) and stay out.
OBS_TIME_PATHS = ("tpu9/serving/", "tpu9/router/", "tpu9/worker/",
                  "tpu9/cache/",
                  "tpu9/runner/", "tpu9/observability/")

# ASY004: call names that block the event loop. Dotted names match exact
# attribute chains; bare names match builtins called by name.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "os.system": "os.system",
    "os.popen": "os.popen",
    "os.wait": "os.wait",
    "os.waitpid": "os.waitpid",
    "subprocess.run": "sync subprocess",
    "subprocess.call": "sync subprocess",
    "subprocess.check_call": "sync subprocess",
    "subprocess.check_output": "sync subprocess",
    "subprocess.getoutput": "sync subprocess",
    "subprocess.getstatusoutput": "sync subprocess",
    "subprocess.Popen": "sync subprocess",
    "socket.create_connection": "sync socket IO",
    "socket.getaddrinfo": "sync DNS resolution",
    "urllib.request.urlopen": "sync HTTP",
    "requests.request": "sync HTTP",
    "requests.get": "sync HTTP",
    "requests.post": "sync HTTP",
    "requests.put": "sync HTTP",
    "requests.delete": "sync HTTP",
    "requests.head": "sync HTTP",
    "shutil.rmtree": "sync file IO",
    "shutil.copytree": "sync file IO",
    "shutil.copy": "sync file IO",
    "shutil.copy2": "sync file IO",
    "shutil.move": "sync file IO",
}

# TMO001 scope: the control/serve planes where an unbounded network
# await parks a request (or a whole dispatcher) behind one hung peer.
TMO_PATHS = ("tpu9/gateway/", "tpu9/router/", "tpu9/runner/",
             "tpu9/worker/", "tpu9/cache/", "tpu9/statestore/")
# aiohttp-style client receivers (last dotted segment) + methods
TMO_SESSION_RECVS = frozenset({"session", "_session", "sess",
                               "_proxy_session", "client_session", "http"})
TMO_HTTP_METHODS = frozenset({"request", "get", "post", "put", "delete",
                              "patch", "head", "options", "ws_connect"})
# statestore ops that BLOCK server-side until their own timeout →
# positional index of that timeout argument (blpop(key, timeout),
# xread(key, last_id, timeout))
TMO_STORE_BLOCKING = {"blpop": 1, "xread": 2}
TMO_TIMEOUT_KWARGS = frozenset({"timeout", "timeout_s", "deadline_s",
                                "total"})

# OBS002: metrics-registry recording methods (receiver must look like a
# Metrics registry: the chain's last segment before the method is
# "metrics") and the identifier stems whose values are per-request /
# per-trace identity — unbounded as label values
METRIC_RECORD_METHODS = ("inc", "observe", "set_gauge")
OBS2_TAINT_NAMES = frozenset({
    "request_id", "req_id", "requestid", "trace_id", "traceid", "span_id",
    "spanid", "parent_id", "task_id", "taskid", "prompt", "prompt_tokens",
    "message_id", "trace",
})

# device->host syncs for JAX001 (attribute-method form, zero/any args)
SYNC_METHODS = {"item", "block_until_ready", "tolist"}
# dotted-call form
SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
    "np.asarray": "np.asarray on a device value",
    "np.array": "np.array on a device value",
    "numpy.asarray": "numpy.asarray on a device value",
    "numpy.array": "numpy.array on a device value",
}


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclass
class _Scope:
    name: str
    is_async: bool
    node: ast.AST
    loop_depth: int = 0


class FileChecker(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._scopes: list[_Scope] = []

    # -- helpers -------------------------------------------------------------
    def _symbol(self) -> str:
        return ".".join(s.name for s in self._scopes
                        if not isinstance(s.node, (ast.For, ast.While,
                                                   ast.AsyncFor))) or "<module>"

    def _fn_scope(self) -> _Scope | None:
        """Nearest enclosing function/lambda scope (loops excluded)."""
        for s in reversed(self._scopes):
            if isinstance(s.node, (ast.AsyncFunctionDef, ast.FunctionDef,
                                   ast.Lambda)):
                return s
        return None

    def _in_async(self) -> bool:
        s = self._fn_scope()
        return s is not None and s.is_async

    def _in_loop(self) -> bool:
        for s in reversed(self._scopes):
            if isinstance(s.node, (ast.For, ast.While, ast.AsyncFor)):
                return True
            if isinstance(s.node, (ast.AsyncFunctionDef, ast.FunctionDef,
                                   ast.Lambda)):
                return False
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule, self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), message, self._symbol()))

    # -- scope tracking ------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scopes.append(_Scope(node.name, False, node))
        self.generic_visit(node)
        self._scopes.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scopes.append(_Scope(node.name, False, node))
        self.generic_visit(node)
        self._scopes.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scopes.append(_Scope(node.name, True, node))
        self.generic_visit(node)
        self._scopes.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._scopes.append(_Scope("<lambda>", False, node))
        self.generic_visit(node)
        self._scopes.pop()

    def _visit_loop(self, node) -> None:
        self._scopes.append(_Scope("<loop>", self._in_async(), node))
        self.generic_visit(node)
        self._scopes.pop()

    visit_For = visit_While = visit_AsyncFor = _visit_loop

    # -- ASY002: discarded task handles --------------------------------------
    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            name = dotted_name(call.func)
            tail = name.rsplit(".", 1)[-1]
            # bare names too: `from asyncio import create_task` is the same
            # weak-ref'd fire-and-forget (a same-named local helper is a
            # reviewed noqa, not a hole in the rule)
            if tail in ("create_task", "ensure_future"):
                self._emit(
                    "ASY002", node,
                    f"fire-and-forget {name}(...): the loop keeps only a "
                    "weak ref, so GC can collect the RUNNING task — hold "
                    "the handle or use tpu9.utils.aio.spawn()")
        self.generic_visit(node)

    # -- ASY001 / ASY004 / JAX002 on calls ------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)

        # ASY001: asyncio.wait_for(<x>.get()/<x>.wait(), ...)
        if name in ("asyncio.wait_for", "wait_for") and node.args:
            inner = node.args[0]
            if (isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr in ("get", "wait")
                    and dotted_name(inner.func.value) != "asyncio"):
                loop_note = (" inside a poll loop" if self._in_loop() else "")
                self._emit(
                    "ASY001", node,
                    f"asyncio.wait_for wrapping .{inner.func.attr}()"
                    f"{loop_note}: py3.10 wait_for can swallow a cancel "
                    "racing the inner future (the Dispatcher._exit_loop "
                    "hang) — use a bare await or "
                    "tpu9.utils.aio.queue_get/event_wait")

        # ASY004: blocking call in async def
        if self._in_async():
            desc = BLOCKING_CALLS.get(name)
            if desc:
                self._emit(
                    "ASY004", node,
                    f"{desc} ({name}) blocks the event loop inside an "
                    "async def — wrap in asyncio.to_thread or use the "
                    "async equivalent")
            elif name == "open":
                self._emit(
                    "ASY004", node,
                    "sync file IO (open) directly in an async def blocks "
                    "the event loop — wrap the IO in asyncio.to_thread")

        # OBS002: unbounded metric-label cardinality
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_RECORD_METHODS
                and dotted_name(node.func.value)
                .rsplit(".", 1)[-1] == "metrics"):
            labels = None
            for kw in node.keywords:
                if kw.arg == "labels":
                    labels = kw.value
            if labels is None and len(node.args) >= 3:
                labels = node.args[2]
            if isinstance(labels, ast.Dict):
                for key_node, val in zip(labels.keys, labels.values):
                    hit = self._obs2_tainted(val)
                    if hit:
                        key_txt = (repr(key_node.value)
                                   if isinstance(key_node, ast.Constant)
                                   else "<computed>")
                        self._emit(
                            "OBS002", node,
                            f"metric label {key_txt} value derives from "
                            f"{hit}: every distinct id mints a permanent "
                            "series (registry + Prometheus exposition "
                            "grow without bound) — put per-request "
                            "identity in span attributes or flight "
                            "records, keep label dimensions bounded "
                            "(stub/tenant/phase/reason)")
                        break       # one finding per call

        # JAX002: jax.jit(...)(...) immediately invoked
        if (isinstance(node.func, ast.Call)
                and dotted_name(node.func.func) in ("jax.jit", "jit",
                                                    "jax.pmap", "pmap")):
            self._emit(
                "JAX002", node,
                f"{dotted_name(node.func.func)}(fn)(...) immediately "
                "invoked: retraces and recompiles on every call — cache "
                "the jitted callable at module or object scope")
        # JAX002: jit constructed inside a loop body
        elif (dotted_name(node.func) in ("jax.jit", "jax.pmap")
              and self._in_loop()):
            self._emit(
                "JAX002", node,
                f"{dotted_name(node.func)} constructed inside a loop: "
                "each iteration builds (and retraces) a fresh callable — "
                "hoist and cache it")

        self.generic_visit(node)

    @staticmethod
    def _obs2_tainted(expr: ast.AST) -> str:
        """Describe the unbounded-identity source inside a label-value
        expression, or ''. Over-approximate by NAME (a false positive
        costs one reviewed rename/suppression; a missed id-valued label
        grows the registry forever): any mention of a request/trace/span/
        task id or prompt identifier — bare, attribute (``req.request_id``),
        formatted into an f-string, or minted inline (``new_trace_id()``)."""
        for n in ast.walk(expr):
            stem = ""
            if isinstance(n, ast.Name):
                stem = n.id
            elif isinstance(n, ast.Attribute):
                stem = n.attr
            elif isinstance(n, ast.Call):
                callee = dotted_name(n.func).rsplit(".", 1)[-1]
                if callee in ("new_trace_id", "new_id", "uuid4", "uuid1"):
                    return f"`{callee}()` (a freshly minted id)"
            if stem.lower() in OBS2_TAINT_NAMES:
                return f"`{stem}`"
        return ""

    # -- TMO001: unbounded network awaits (ISSUE 15) ---------------------------
    def _tmo_check(self, call: ast.AST) -> None:
        if not isinstance(call, ast.Call):
            return
        hit = self._tmo_unbounded(call)
        if hit:
            self._emit(
                "TMO001", call,
                f"{hit} awaited without a timeout/deadline: a "
                "hung peer parks this caller forever — pass "
                "timeout=/ClientTimeout, or wrap in "
                "asyncio.wait_for / tpu9.utils.aio."
                "cancellable_wait")

    def visit_Await(self, node: ast.Await) -> None:
        if self.path.startswith(TMO_PATHS):
            self._tmo_check(node.value)
        self.generic_visit(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        # `async with session.post(...) as resp:` — the aiohttp idiom —
        # awaits the request in __aenter__, not through an Await node
        if self.path.startswith(TMO_PATHS):
            for item in node.items:
                self._tmo_check(item.context_expr)
        self.generic_visit(node)

    @staticmethod
    def _tmo_unbounded(call: ast.Call) -> str:
        """Describe the unbounded network call, or ''. Presence of any
        timeout-ish kwarg (or a positional blocking-timeout for blpop/
        xread) satisfies the rule — value audit is the reviewer's job."""
        kwargs = {kw.arg for kw in call.keywords if kw.arg}
        if kwargs & TMO_TIMEOUT_KWARGS:
            return ""
        name = dotted_name(call.func)
        if name in ("asyncio.open_connection", "open_connection"):
            return f"`{name}(...)`"
        if not isinstance(call.func, ast.Attribute):
            return ""
        meth = call.func.attr
        recv_tail = dotted_name(call.func.value).rsplit(".", 1)[-1]
        if (meth in TMO_HTTP_METHODS
                and recv_tail.lower() in TMO_SESSION_RECVS):
            return f"HTTP client call `{recv_tail}.{meth}(...)`"
        if meth in TMO_STORE_BLOCKING:
            # a positional block-timeout (blpop(key, 5)) counts
            if len(call.args) <= TMO_STORE_BLOCKING[meth]:
                return f"blocking store read `.{meth}(...)`"
        return ""

    # -- ASY003: swallowed cancellation ---------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._in_async():
            caught = self._cancellation_catchers(node.type)
            if caught and not self._reraises(node):
                self._emit(
                    "ASY003", node,
                    f"{caught} in a coroutine without re-raising: swallows "
                    "CancelledError, so cancellation (shutdown, timeout, "
                    "drain) silently keeps the coroutine alive — re-raise "
                    "or narrow to `except Exception`")
        self.generic_visit(node)

    @staticmethod
    def _cancellation_catchers(typ: ast.AST | None) -> str:
        """Describe the clause if it catches CancelledError; '' if not."""
        if typ is None:
            return "bare `except:`"
        names = []
        if isinstance(typ, ast.Tuple):
            names = [dotted_name(e) for e in typ.elts]
        else:
            names = [dotted_name(typ)]
        for n in names:
            if n.rsplit(".", 1)[-1] in ("BaseException", "CancelledError"):
                return f"`except {n}`"
        return ""

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        # a raise inside a NESTED def/lambda is that function's raise, not
        # this handler's — don't let it silence the rule
        nested: set[int] = set()
        for n in ast.walk(handler):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and id(n) not in nested:
                nested.update(id(x) for x in ast.walk(n))
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise) and id(n) not in nested:
                return True
        return False


def check_file(path: str, tree: ast.AST) -> list[Finding]:
    checker = FileChecker(path)
    checker.visit(tree)
    checker.findings.extend(check_obs_time(path, tree))
    return checker.findings


# -- OBS001: wall-clock durations/deadlines in hot-path planes ----------------

def _is_walltime_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in ("time.time", "_time.time"))


def _assign_pairs(node: ast.Assign):
    """(target, value) pairs, unpacking parallel tuple assignments so
    ``a, b = time.monotonic(), time.time()`` taints only ``b``."""
    for t in node.targets:
        if (isinstance(t, ast.Tuple) and isinstance(node.value, ast.Tuple)
                and len(t.elts) == len(node.value.elts)):
            yield from zip(t.elts, node.value.elts)
        else:
            yield t, node.value


def check_obs_time(path: str, tree: ast.AST) -> list[Finding]:
    """OBS001: flag +/-/comparison arithmetic on wall-clock values in the
    scoped planes. Taint is deliberately over-approximate (an attribute
    NAME assigned ``time.time()`` anywhere in the file taints that
    attribute file-wide; a local name taints its enclosing function) — a
    false positive costs one reviewed suppression, a stepped-clock
    duration corrupts admission deadlines and latency evidence."""
    if not path.startswith(OBS_TIME_PATHS):
        return []

    wall_attrs: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign):
            for tgt, val in _assign_pairs(n):
                if _is_walltime_call(val) and isinstance(tgt, ast.Attribute):
                    wall_attrs.add(tgt.attr)

    findings: list[Finding] = []

    def scan_scope(owner: ast.AST, qualname: str) -> None:
        # names assigned from time.time() in THIS scope's own body
        nested: set[int] = set()
        for c in ast.walk(owner):
            if (isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)) and c is not owner
                    and id(c) not in nested):
                nested.update(id(x) for x in ast.walk(c))
        own = [n for n in ast.walk(owner) if id(n) not in nested]
        wall_names = {tgt.id for n in own if isinstance(n, ast.Assign)
                      for tgt, val in _assign_pairs(n)
                      if _is_walltime_call(val) and isinstance(tgt, ast.Name)}

        def tainted(node: ast.AST) -> str:
            if _is_walltime_call(node):
                return "time.time()"
            if isinstance(node, ast.Name) and node.id in wall_names:
                return f"`{node.id}` (assigned from time.time())"
            if isinstance(node, ast.Attribute) and node.attr in wall_attrs:
                return (f"`.{node.attr}` (an attribute assigned from "
                        "time.time() in this file)")
            return ""

        for n in own:
            operands = []
            if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Add,
                                                              ast.Sub)):
                operands = [n.left, n.right]
            elif isinstance(n, ast.Compare):
                operands = [n.left, *n.comparators]
            for op in operands:
                hit = tainted(op)
                if hit:
                    findings.append(Finding(
                        "OBS001", path, n.lineno, n.col_offset,
                        f"wall-clock arithmetic on {hit}: durations and "
                        "deadlines must come from time.monotonic() — an "
                        "NTP step makes this negative or fire early/late; "
                        "keep time.time() only as a stored wall anchor",
                        qualname))
                    break           # one finding per expression

    def walk_defs(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                scan_scope(child, qual)
                walk_defs(child, qual)
            elif isinstance(child, ast.Lambda):
                # lambdas are scopes too (scan_scope excludes their bodies
                # from the enclosing scope): a deadline lambda like
                # `lambda: time.time() > deadline` must not slip through
                qual = f"{prefix}.<lambda>" if prefix else "<lambda>"
                scan_scope(child, qual)
                walk_defs(child, qual)
            elif isinstance(child, ast.ClassDef):
                walk_defs(child, f"{prefix}.{child.name}" if prefix
                          else child.name)
            else:
                walk_defs(child, prefix)

    scan_scope(tree, "<module>")
    walk_defs(tree, "")
    return findings


# -- JAX001: whole-program hot-path sync check --------------------------------

@dataclass
class _FnInfo:
    path: str
    qualname: str
    node: ast.AST
    calls: set[str] = field(default_factory=set)


def _collect_functions(path: str, tree: ast.AST) -> list[_FnInfo]:
    fns: list[_FnInfo] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                info = _FnInfo(path, qual, child)
                for n in ast.walk(child):
                    if isinstance(n, ast.Call):
                        name = dotted_name(n.func)
                        if name:
                            info.calls.add(name.rsplit(".", 1)[-1])
                fns.append(info)
                walk(child, qual)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}.{child.name}" if prefix
                     else child.name)
            else:
                walk(child, prefix)

    walk(tree, "")
    return fns


def check_jax_hotpath(files: dict[str, ast.AST], roots: list[str],
                      ) -> list[Finding]:
    """BFS the name-linked call graph from ``roots`` (bare function names)
    across the hot-path files; flag host-device syncs in reachable fns."""
    all_fns: list[_FnInfo] = []
    for path, tree in sorted(files.items()):
        all_fns.extend(_collect_functions(path, tree))
    by_bare: dict[str, list[_FnInfo]] = {}
    for fn in all_fns:
        by_bare.setdefault(fn.qualname.rsplit(".", 1)[-1], []).append(fn)

    reachable: set[int] = set()
    frontier = [fn for r in roots for fn in by_bare.get(r, [])]
    while frontier:
        fn = frontier.pop()
        if id(fn) in reachable:
            continue
        reachable.add(id(fn))
        for callee in fn.calls:
            frontier.extend(by_bare.get(callee, []))

    findings: list[Finding] = []
    for fn in all_fns:
        if id(fn) not in reachable:
            continue
        # scan only this function's own body, not nested defs (they are
        # separate graph nodes and may be unreachable trace-time closures)
        nested_nodes: set[int] = set()
        for c in ast.walk(fn.node):
            if (isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and c is not fn.node and id(c) not in nested_nodes):
                nested_nodes.update(id(x) for x in ast.walk(c))

        for n in ast.walk(fn.node):
            if not isinstance(n, ast.Call) or id(n) in nested_nodes:
                continue
            name = dotted_name(n.func)
            sync = SYNC_CALLS.get(name)
            if (not sync and isinstance(n.func, ast.Attribute)
                    and n.func.attr in SYNC_METHODS
                    and not n.args):
                sync = f".{n.func.attr}()"
            if sync:
                findings.append(Finding(
                    "JAX001", fn.path, n.lineno, n.col_offset,
                    f"host-device sync ({sync}) in `{fn.qualname}`, which "
                    f"is reachable from the serve loop "
                    f"({'/'.join(roots)}): every sync stalls the decode "
                    "pipeline — batch it at the window boundary or keep a "
                    "host mirror", fn.qualname))
    return findings
