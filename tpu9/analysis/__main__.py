"""CLI entry: ``python -m tpu9.analysis``.

Exit codes: 0 clean (or everything known/suppressed), 1 new findings,
2 internal/parse errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from .findings import (JSON_SCHEMA_VERSION, finding_json, load_baseline)
from .runner import (ALL_RULES, DEFAULT_BASELINE, DEFAULT_ROOTS,
                     find_repo_root, gate, run_analysis)


def _run_all(args) -> int:
    """``python -m tpu9.analysis --all`` (ISSUE 18): every analysis plane
    behind one exit code and one JSON stream on the shared finding
    schema. Each tool gates against its own triaged baseline; exit is
    the max of the per-tool codes (0 clean, 1 findings, 2 errors)."""
    import os

    from .wirecheck import DEFAULT_BASELINE as WIRE_BASELINE
    from .wirecheck import run_wirecheck

    repo_root = args.repo_root or find_repo_root()

    def _bl(path):
        return load_baseline(path if os.path.isabs(path)
                             else os.path.join(repo_root, path))

    tools = []          # (name, result, new, known, extra_findings)
    rc = 0

    lint_res = run_analysis(repo_root)
    lnew, lknown, _ = gate(lint_res, _bl(DEFAULT_BASELINE))
    tools.append(("tpu9lint", lint_res, lnew, lknown, []))

    wire_res = run_wirecheck(repo_root)
    wnew, wknown, _ = _bl(WIRE_BASELINE).split(wire_res.findings)
    tools.append(("wirecheck", wire_res, wnew, wknown, []))

    matrix_report = None
    if not args.static_only:
        from .graphcheck import passes
        from .graphcheck.matrix import find_cells
        guard = passes.device_guard()
        if guard is not None:
            print(f"tpu9.analysis --all: graphcheck matrix SKIP — {guard}",
                  file=sys.stderr)
        else:
            matrix_report = passes.run_matrix(find_cells(None))
            tools.append(("graphcheck", None, [], [],
                          list(matrix_report["findings"])))

    records = []
    for name, res, new, known, extra in tools:
        for f in new + extra:
            records.append(finding_json(f, "new") | {"tool": name})
        for f in known:
            records.append(finding_json(f, "baselined") | {"tool": name})
        if res is not None and res.parse_errors:
            rc = max(rc, 2)
        if new or extra:
            rc = max(rc, 1)

    if args.format == "json":
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "tool": "tpu9.analysis",
            "tools": [name for name, *_ in tools],
            "findings": records,
            "parse_errors": [e for _, res, *_ in tools if res
                             for e in res.parse_errors],
        }, indent=1))
    else:
        for name, res, new, known, extra in tools:
            for f in new + extra:
                print(f"{name}: {f.format()}")
            if res is not None:
                print(f"{name}: {res.files_scanned} files in "
                      f"{res.elapsed_s:.2f}s — {len(new)} new, "
                      f"{len(known)} baselined")
            elif matrix_report is not None:
                print(f"graphcheck: {len(matrix_report['cells'])} cells "
                      f"in {matrix_report['elapsed_s']:.1f}s — "
                      f"{len(extra)} findings")
        print(f"tpu9.analysis --all: {'FAIL' if rc else 'OK'}")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu9.analysis",
        description="tpu9lint: async-cancellation / JAX hot-path / "
                    "module-boundary static analysis")
    ap.add_argument("roots", nargs="*", default=None,
                    help=f"paths to scan (default: {', '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="triaged baseline json (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="legacy json dump (prefer --format json)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format; json emits the stable "
                         "machine-readable schema shared with graphcheck "
                         "(file/line/col/rule/symbol/message/fingerprint/"
                         "status records)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-known", action="store_true",
                    help="also print baselined findings")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="run every analysis plane — tpu9lint (incl. the "
                         "graphcheck AST rules), wirecheck, and the "
                         "graphcheck lowering matrix — with one exit code "
                         "and one JSON stream")
    ap.add_argument("--static-only", action="store_true",
                    help="with --all: skip the graphcheck lowering matrix "
                         "(AST-only, no jax imports)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in ALL_RULES.items():
            print(f"{rid}  {desc}")
        return 0

    if args.run_all:
        return _run_all(args)

    repo_root = args.repo_root or find_repo_root()
    roots = args.roots or DEFAULT_ROOTS
    select = ({r.strip() for r in args.select.split(",") if r.strip()}
              or None)
    result = run_analysis(repo_root, roots, select=select)

    if args.no_baseline:
        new, known, stale = result.findings, [], []
    else:
        import os
        bl_path = args.baseline
        if bl_path and not os.path.isabs(bl_path):
            bl_path = os.path.join(repo_root, bl_path)
        new, known, stale = gate(result, load_baseline(bl_path))
        # a scoped/filtered run can't see the whole baseline — only report
        # staleness for entries the run actually covered
        if args.roots:
            stale = [e for e in stale
                     if any(e.get("path", "") == r.rstrip("/")
                            or e.get("path", "").startswith(
                                r.rstrip("/") + "/")
                            for r in args.roots)]
        if select:
            stale = [e for e in stale if e.get("rule") in select]

    if args.format == "json":
        # the stable CI schema (ISSUE 11): one record shape for lint +
        # graphcheck findings, round-trip tested in tests/test_graphcheck
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "tool": "tpu9lint",
            "files_scanned": result.files_scanned,
            "elapsed_s": round(result.elapsed_s, 3),
            "findings": [finding_json(f, "new") for f in new]
            + [finding_json(f, "baselined") for f in known],
            "stale": [e["fingerprint"] for e in stale],
            "suppressed_inline": len(result.suppressed),
            "parse_errors": result.parse_errors,
        }, indent=1))
    elif args.as_json:
        print(json.dumps({
            "files_scanned": result.files_scanned,
            "elapsed_s": round(result.elapsed_s, 3),
            "new": [f.to_dict() | {"line": f.line} for f in new],
            "known": [f.fingerprint for f in known],
            "stale": [e["fingerprint"] for e in stale],
            "suppressed_inline": len(result.suppressed),
            "parse_errors": result.parse_errors,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if args.show_known:
            for f in known:
                print(f"known    {f.format()}")
        for e in stale:
            print(f"stale baseline entry (finding no longer fires — prune "
                  f"it): {e['rule']} {e['path']} [{e.get('symbol')}] "
                  f"{e['fingerprint']}")
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
        counts = ", ".join(f"{r}={n}" for r, n in sorted(
            {**{}, **result.by_rule()}.items()))
        print(f"tpu9lint: {result.files_scanned} files in "
              f"{result.elapsed_s:.2f}s — {len(new)} new, {len(known)} "
              f"baselined, {len(result.suppressed)} noqa'd"
              + (f" ({counts})" if counts else ""))

    if result.parse_errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
