"""CLI entry: ``python -m tpu9.analysis``.

Exit codes: 0 clean (or everything known/suppressed), 1 new findings,
2 internal/parse errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from .findings import (JSON_SCHEMA_VERSION, finding_json, load_baseline)
from .runner import (ALL_RULES, DEFAULT_BASELINE, DEFAULT_ROOTS,
                     find_repo_root, gate, run_analysis)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu9.analysis",
        description="tpu9lint: async-cancellation / JAX hot-path / "
                    "module-boundary static analysis")
    ap.add_argument("roots", nargs="*", default=None,
                    help=f"paths to scan (default: {', '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="triaged baseline json (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="legacy json dump (prefer --format json)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format; json emits the stable "
                         "machine-readable schema shared with graphcheck "
                         "(file/line/col/rule/symbol/message/fingerprint/"
                         "status records)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-known", action="store_true",
                    help="also print baselined findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in ALL_RULES.items():
            print(f"{rid}  {desc}")
        return 0

    repo_root = args.repo_root or find_repo_root()
    roots = args.roots or DEFAULT_ROOTS
    select = ({r.strip() for r in args.select.split(",") if r.strip()}
              or None)
    result = run_analysis(repo_root, roots, select=select)

    if args.no_baseline:
        new, known, stale = result.findings, [], []
    else:
        import os
        bl_path = args.baseline
        if bl_path and not os.path.isabs(bl_path):
            bl_path = os.path.join(repo_root, bl_path)
        new, known, stale = gate(result, load_baseline(bl_path))
        # a scoped/filtered run can't see the whole baseline — only report
        # staleness for entries the run actually covered
        if args.roots:
            stale = [e for e in stale
                     if any(e.get("path", "") == r.rstrip("/")
                            or e.get("path", "").startswith(
                                r.rstrip("/") + "/")
                            for r in args.roots)]
        if select:
            stale = [e for e in stale if e.get("rule") in select]

    if args.format == "json":
        # the stable CI schema (ISSUE 11): one record shape for lint +
        # graphcheck findings, round-trip tested in tests/test_graphcheck
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "tool": "tpu9lint",
            "files_scanned": result.files_scanned,
            "elapsed_s": round(result.elapsed_s, 3),
            "findings": [finding_json(f, "new") for f in new]
            + [finding_json(f, "baselined") for f in known],
            "stale": [e["fingerprint"] for e in stale],
            "suppressed_inline": len(result.suppressed),
            "parse_errors": result.parse_errors,
        }, indent=1))
    elif args.as_json:
        print(json.dumps({
            "files_scanned": result.files_scanned,
            "elapsed_s": round(result.elapsed_s, 3),
            "new": [f.to_dict() | {"line": f.line} for f in new],
            "known": [f.fingerprint for f in known],
            "stale": [e["fingerprint"] for e in stale],
            "suppressed_inline": len(result.suppressed),
            "parse_errors": result.parse_errors,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if args.show_known:
            for f in known:
                print(f"known    {f.format()}")
        for e in stale:
            print(f"stale baseline entry (finding no longer fires — prune "
                  f"it): {e['rule']} {e['path']} [{e.get('symbol')}] "
                  f"{e['fingerprint']}")
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
        counts = ", ".join(f"{r}={n}" for r, n in sorted(
            {**{}, **result.by_rule()}.items()))
        print(f"tpu9lint: {result.files_scanned} files in "
              f"{result.elapsed_s:.2f}s — {len(new)} new, {len(known)} "
              f"baselined, {len(result.suppressed)} noqa'd"
              + (f" ({counts})" if counts else ""))

    if result.parse_errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
