"""BND001 — declarative import-boundary contracts (boundaries.toml).

Three contract kinds, all prefix-matched on module paths (most specific
``allow``/``forbid`` key wins; intra-package imports are always allowed):

- ``[allow]``:  package -> exhaustive list of tpu9 package prefixes it may
  import. Anything else under ``tpu9.`` is a violation. This is the strong
  form used for the serving/router/ops layers the engine split must keep
  clean.
- ``[forbid]``: package -> explicit prohibitions, for packages whose full
  import surface is not worth enumerating (gateway, worker).
- ``[restricted]``: module -> the only importer prefixes allowed to touch
  it. Used for the raw-KV-dtype boundary: ``tpu9.ops.quant`` is where KV
  int8 layouts live, and only the model/serving stack may see them.

The checker resolves relative imports to absolute module paths, so ``from
..ops import quant`` inside ``tpu9/serving/engine.py`` is correctly seen as
``tpu9.ops.quant``.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from . import tomlmini
from .findings import Finding


@dataclass
class BoundaryConfig:
    allow: dict[str, list[str]] = field(default_factory=dict)
    forbid: dict[str, list[str]] = field(default_factory=dict)
    restricted: dict[str, list[str]] = field(default_factory=dict)
    jax_hotpath_files: list[str] = field(default_factory=list)
    jax_roots: list[str] = field(default_factory=list)
    # [graphcheck] table: scope/owner/carrier declarations for the
    # SHD001/DTY001 rules (tpu9.analysis.graphcheck.astrules)
    graph: dict = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "BoundaryConfig":
        raw = tomlmini.load_file(path)
        jax = raw.get("jax", {}).get("hotpath", {})
        return cls(allow=raw.get("allow", {}),
                   forbid=raw.get("forbid", {}),
                   restricted=raw.get("restricted", {}),
                   jax_hotpath_files=jax.get("files", []),
                   jax_roots=jax.get("roots", []),
                   graph=raw.get("graphcheck", {}))


def module_name(path: str) -> str:
    """'tpu9/serving/engine.py' -> 'tpu9.serving.engine' (pkg __init__
    collapses to the package)."""
    mod = path[:-3].replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def extract_imports(path: str, tree: ast.AST) -> list[tuple[str, int]]:
    """Absolute tpu9.* module targets imported by this file, with lineno.

    ``from X import name`` records ``X.name`` (the deepest plausible module
    path) — prefix matching in the contracts means a rule on ``X`` still
    covers it, while a rule on a submodule ``X.name`` bites too.
    """
    mod = module_name(path)
    is_pkg = path.endswith("__init__.py")
    out: list[tuple[str, int]] = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name.startswith("tpu9"):
                    out.append((a.name, n.lineno))
        elif isinstance(n, ast.ImportFrom):
            if n.level:
                parts = mod.split(".")
                if not is_pkg:
                    parts = parts[:-1]
                parts = parts[: len(parts) - n.level + 1]
                base = ".".join(parts)
                target = f"{base}.{n.module}" if n.module else base
            else:
                target = n.module or ""
            if target.startswith("tpu9"):
                names = [a.name for a in n.names if a.name != "*"]
                if names:
                    out.extend((f"{target}.{a}", n.lineno) for a in names)
                else:
                    out.append((target, n.lineno))
    return out


def _prefix_of(module: str, prefix: str) -> bool:
    return module == prefix or module.startswith(prefix + ".")


def _best_key(module: str, keys) -> str | None:
    best = None
    for k in keys:
        if _prefix_of(module, k) and (best is None or len(k) > len(best)):
            best = k
    return best


def check_boundaries(files: dict[str, ast.AST],
                     cfg: BoundaryConfig) -> list[Finding]:
    findings: list[Finding] = []
    for path in sorted(files):
        mod = module_name(path)
        seen: set[str] = set()
        for target, lineno in extract_imports(path, files[path]):
            if target in seen:
                continue
            seen.add(target)

            akey = _best_key(mod, cfg.allow)
            if (akey is not None and not _prefix_of(target, akey)
                    and not any(_prefix_of(target, a)
                                for a in cfg.allow[akey])):
                findings.append(Finding(
                    "BND001", path, lineno, 0,
                    f"`{mod}` imports `{target}` but its contract "
                    f"([allow] \"{akey}\") only permits "
                    f"{cfg.allow[akey] or '[] (leaf package)'} — the "
                    "boundary the engine split depends on",
                    symbol=target))

            fkey = _best_key(mod, cfg.forbid)
            if fkey is not None:
                for bad in cfg.forbid[fkey]:
                    if _prefix_of(target, bad):
                        findings.append(Finding(
                            "BND001", path, lineno, 0,
                            f"`{mod}` imports `{target}`, forbidden by "
                            f"[forbid] \"{fkey}\" -> {bad}",
                            symbol=target))
                        break

            for rmod, importers in cfg.restricted.items():
                if _prefix_of(target, rmod) and not any(
                        _prefix_of(mod, imp) for imp in importers):
                    findings.append(Finding(
                        "BND001", path, lineno, 0,
                        f"`{mod}` imports `{target}`: [restricted] "
                        f"\"{rmod}\" may only be touched by {importers} "
                        "(raw KV dtypes / engine internals stay behind "
                        "their boundary)", symbol=target))
    return findings
