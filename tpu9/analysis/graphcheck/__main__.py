"""CLI entry: ``python -m tpu9.analysis.graphcheck``.

Runs Pass A (abstract lowering over the preset × topology matrix) and
Pass B (the SHD001/SHD002/DTY001 AST rules through the normal tpu9lint
gate, baseline + suppressions applied).

Exit codes: 0 clean, 1 findings, 2 internal errors, 3 device guard
tripped (no forced 8-device CPU mesh available — the report says how to
re-run; ``--skip-ok`` maps it to 0 for wrappers that handle the skip
themselves).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu9.analysis.graphcheck",
        description="static verification of sharding/dtype/donation "
                    "invariants in the traced serving graphs")
    ap.add_argument("--cell", action="append", default=None,
                    help="run only this matrix cell (repeatable); "
                         "default: the full matrix")
    ap.add_argument("--list-cells", action="store_true")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the compiled-artifact checks (aliasing, "
                         "input shardings) — jaxpr-level only, faster")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip Pass B (the AST rules)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format (json: the stable machine-"
                         "readable schema shared with tpu9lint)")
    ap.add_argument("--skip-ok", action="store_true",
                    help="exit 0 (not 3) when the device guard trips")
    ap.add_argument("--repo-root", default=None)
    args = ap.parse_args(argv)

    from .matrix import MATRIX, find_cells
    if args.list_cells:
        for c in MATRIX:
            print(c.name)
        return 0

    # the 8-device CPU mesh must be forced BEFORE jax latches a platform
    from tpu9.utils import force_cpu
    force_cpu(host_devices=8)

    from ..findings import (JSON_SCHEMA_VERSION, finding_json,
                            load_baseline)
    from ..runner import (DEFAULT_BASELINE, find_repo_root, gate,
                          run_analysis)
    from .astrules import GRAPH_AST_RULES
    from . import passes

    guard = passes.device_guard()
    if guard is not None:
        print(f"graphcheck: SKIP — {guard}", file=sys.stderr)
        return 0 if args.skip_ok else 3

    try:
        cells = find_cells(args.cell)
    except KeyError as exc:
        print(f"graphcheck: {exc}", file=sys.stderr)
        return 2

    report = passes.run_matrix(cells, compile_jobs=not args.no_compile)
    graph_findings = list(report["findings"])

    lint_new = []
    if not args.no_lint:
        import os
        repo_root = args.repo_root or find_repo_root()
        result = run_analysis(repo_root, select=set(GRAPH_AST_RULES))
        bl_path = os.path.join(repo_root, DEFAULT_BASELINE)
        lint_new, _known, _stale = gate(result, load_baseline(bl_path))
    findings = graph_findings + lint_new

    if args.format == "json":
        # same record schema as `python -m tpu9.analysis --format json`
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "tool": "graphcheck",
            "cells": report["cells"],
            "elapsed_s": report["elapsed_s"],
            "findings": [finding_json(f, "graph") for f in graph_findings]
            + [finding_json(f, "new") for f in lint_new],
        }, indent=1))
    else:
        for f in findings:
            print(f.format())
        cells_s = ", ".join(f"{s['cell']}({s['jobs']} graphs, "
                            f"{s['elapsed_s']}s)" for s in report["cells"])
        print(f"graphcheck: {len(report['cells'])} cells in "
              f"{report['elapsed_s']}s — {len(findings)} findings "
              f"({len(lint_new)} from Pass B)")
        print(f"  {cells_s}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
