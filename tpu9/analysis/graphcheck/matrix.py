"""The declared preset × topology verification matrix (ISSUE 11).

Each cell names a preset and a topology plus the engine knobs graphcheck
lowers the serving graphs with. Every check is shape-level (jaxpr +
lowered/compiled artifact on a forced CPU mesh), so cells are
depth-reduced: ``n_layers=2`` keeps flagship-shaped per-layer tensors
(the sharding/dtype/donation invariants are per-layer identical — layer
3 traces the same eqns as layer 2) while the full matrix stays inside
the tier-1 budget (<120 s). Per-layer SHAPES are never reduced: head
counts, head_dim, hidden/vocab dims are the flagship's, so divisibility
(the silent-replication trap) is checked against the real arithmetic.

Extending the matrix when adding a preset or a graph: add a Cell (or a
knob) here; Pass A derives everything else from the GraphFactory's own
``lowering_jobs`` enumeration, so a new graph is covered the moment
precompile knows it.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Cell:
    preset: str
    topology: str                 # "1x1", "2x1", "2x2", ...
    quantize: str = ""            # "" | "int8" weight quantization
    kv_quant: str = ""            # "" | "int8" paged-KV pool
    n_layers: int = 2             # depth reduction (shapes stay flagship)
    paged: bool = True            # False = legacy dense-cache graph set
    max_batch: int = 2
    max_seq_len: int = 256
    kv_block_size: int = 64
    chunk: int = 128              # prefill chunk (paged) / smallest bucket
    prefill_buckets: tuple = (128, 256)   # dense-mode buckets
    decode_steps: tuple = (1, 4)
    spec_len: int = 4             # speculative-verify graph length
    admit_group_chunks: int = 2   # fused admission group size
    kv_pool_blocks: int = 8

    @property
    def name(self) -> str:
        tags = [t for t in (self.quantize and f"w{self.quantize}",
                            self.kv_quant and f"kv{self.kv_quant}",
                            "" if self.paged else "dense") if t]
        return f"{self.preset}@{self.topology}" + \
            ("+" + "+".join(tags) if tags else "")


# The shipped matrix. Flagship presets × {1x1, tp=2, 2x2} is the floor
# (ISSUE 11); the quantized and MoE cells cover the int8 scale planes
# and per-expert sharding, the dense cell the legacy bucket/dsplice
# graph set.
MATRIX: tuple = (
    # flagship: the config the v5e serving economics are priced on
    Cell("llama3-8b", "1x1"),
    Cell("llama3-8b", "2x1"),
    Cell("llama3-8b", "2x2"),
    # quantized serving end-to-end: int8 weights + int8 paged KV — the
    # scale planes must ride the same head-axis specs as the payload
    Cell("llama3-8b", "2x1", quantize="int8", kv_quant="int8"),
    # second flagship family: 16 KV heads, 256-wide heads
    Cell("gemma-7b", "1x1"),
    Cell("gemma-7b", "2x1"),
    Cell("gemma-7b", "2x2"),
    # MoE flagship: stacked per-expert tensors shard over tp too
    Cell("mixtral-8x7b", "1x1"),
    Cell("mixtral-8x7b", "2x1"),
    Cell("mixtral-8x7b", "2x2"),
    # legacy dense cache: prefill buckets + dense splice graphs
    Cell("llama3-8b", "2x1", paged=False),
)


def find_cells(names=None) -> list:
    """Subset the matrix by cell name (None = all), loudly rejecting
    unknown names so a typo'd --cell can't silently verify nothing."""
    if not names:
        return list(MATRIX)
    by_name = {c.name: c for c in MATRIX}
    out = []
    for n in names:
        if n not in by_name:
            raise KeyError(
                f"unknown graphcheck cell {n!r}; have {sorted(by_name)}")
        out.append(by_name[n])
    return out
