"""Pass A — abstract lowering + invariant verification (ISSUE 11).

For each matrix cell this module builds the EXACT objects the serving
engine would build — DecoderConfig, EngineConfig, sharding policy,
GraphFactory — hands the factory abstract (``ShapeDtypeStruct``) state,
and verifies every graph the factory enumerates, without allocating a
buffer or touching a device:

- **GRA001** weight sharding: under tp>1 every weight leaf the layout
  rule declares sharded must RESOLVE sharded (the divisibility fallback
  silently replicates otherwise — all the HBM, none of the capacity),
  at least one tp-sharded matmul operand must exist per cell, and the
  compiled executable's input shardings must match the policy's resolved
  specs leaf-for-leaf.
- **GRA002** KV constraint: every KV-state output of every graph must be
  produced by ``sharding_constraint`` carrying the policy's declared
  head-axis spec (through ``lax.scan`` carries too), and the compiled
  output shardings must keep the head axis — so a donation round-trip
  can never hand GSPMD an excuse to gather the pool. On 1x1 the SAME
  check inverts: no constraint op may exist at all (the bit-identical
  single-device graph contract).
- **GRA003** donation: the pool/cache/scratch argument of every
  round-trip graph must be declared donated, and every donated leaf must
  be genuinely aliased in the compiled executable
  (``input_output_alias``) — a dropped alias is a silent full-pool copy
  per window.
- **GRA004** dtype closure: no ``dot_general`` anywhere in the jaxpr
  (scan bodies included) takes an int8 operand; scratch/gather outputs
  stay the model dtype; on an int8 pool the payload leaves stay int8 and
  the scale planes f32 through every writer.
- **GRA005** closed signatures: the factory's ``lowering_jobs`` key set
  equals its ``reachable_keys`` set — steady-state serving provably
  cannot hit an uncompiled executable-cache key.
"""

from __future__ import annotations

import re
import time
from dataclasses import replace
from typing import Any, Optional

from ..findings import Finding
from .matrix import MATRIX, Cell

# expected donation per graph kind: (argument index, human name). The
# pool/cache/scratch round-trip buffers MUST be donated — an undonated
# pool doubles HBM traffic per window.
EXPECTED_DONATION = {
    "decode": ((1, "kv_cache"),),
    "verify": ((1, "kv_cache"),),
    "chunk": ((3, "scratch"),),
    "splice": ((0, "pool"),),
    "chunkgroup": ((1, "pool"), (2, "scratch")),
    "dsplice": ((0, "cache k"), (1, "cache v")),
    "prefill": (),
    "gather": (),
}

KV_NAMES = ("k", "v", "k_scale", "v_scale", "table")

# graph kinds whose argument 0 is the weight tree (GRA001's subject);
# the splice/gather/dsplice plumbing graphs take only KV state
PARAMS_KINDS = ("decode", "verify", "chunk", "chunkgroup", "prefill")


def _aliased_params(hlo_text: str) -> set:
    """Entry-parameter numbers aliased to an output in a compiled HLO
    module's ``input_output_alias={ {out}: (param, {}, kind), ... }``
    header (brace-balanced scan — entries nest braces)."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return set()
    i = start + len("input_output_alias=")
    depth = 0
    for j in range(i, len(hlo_text)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    body = hlo_text[i:j + 1]
    return {int(p) for p in re.findall(r"\}:\s*\((\d+)\s*,", body)}


def kind_of(key) -> str:
    if isinstance(key, tuple):
        return key[0]
    return "prefill" if isinstance(key, int) else key


def _f(rule: str, cell_name: str, key, message: str) -> Finding:
    return Finding(rule, f"graph://{cell_name}", 0, 0, message,
                   symbol=str(key))


# -- cell construction --------------------------------------------------------

def build_cell(cell: Cell):
    """(cfg, ecfg, policy, factory, params, state, buckets, spec_lens) —
    the exact objects an engine of this cell would hold, all abstract."""
    from tpu9.serving import EngineConfig
    from tpu9.serving.graphs import GraphFactory, abstract_state
    from tpu9.serving.presets import abstract_params_for, resolve_preset
    from tpu9.serving.shard import make_policy

    cfg, quantized = resolve_preset(cell.preset, cell.quantize or None)
    cfg = replace(cfg, n_layers=cell.n_layers)
    ecfg = EngineConfig(
        max_batch=cell.max_batch, max_seq_len=cell.max_seq_len,
        prefill_buckets=(cell.prefill_buckets if not cell.paged
                         else (cell.chunk, cell.max_seq_len)),
        decode_steps=cell.decode_steps,
        kv_block_size=cell.kv_block_size if cell.paged else 0,
        kv_pool_blocks=cell.kv_pool_blocks,
        prefill_chunk=cell.chunk if cell.paged else 0,
        spec_len=cell.spec_len,
        kv_quant=cell.kv_quant,
        admit_group_chunks=cell.admit_group_chunks)
    policy = make_policy(cell.topology)
    params = abstract_params_for(cfg, quantized)
    state = abstract_state(cfg, ecfg, policy, kv_quant=bool(cell.kv_quant))
    # the engine's own bucket clamping (_bucket_for)
    buckets = sorted({min(bk, ecfg.max_seq_len)
                      for bk in ecfg.prefill_buckets})
    spec_lens = (ecfg.spec_len,) if ecfg.spec_len > 0 else ()
    factory = GraphFactory(cfg, ecfg, policy,
                           chunk=cell.chunk if cell.paged else 0,
                           kv_quant=bool(cell.kv_quant))
    return cfg, ecfg, policy, factory, params, state, buckets, spec_lens


# -- jaxpr helpers ------------------------------------------------------------

def _producer(jaxpr, var):
    """The eqn producing ``var`` in this jaxpr, or None (invar/literal)."""
    for eqn in jaxpr.eqns:
        if any(v is var for v in eqn.outvars):
            return eqn
    return None


def constraint_for_output(jaxpr, var):
    """The ``sharding_constraint`` sharding pinning ``var``, descending
    into scan carries (the fused-admission pool rides a scan carry whose
    constraint lives in the body). None when the output is unpinned."""
    eqn = _producer(jaxpr, var)
    if eqn is None:
        return None
    name = eqn.primitive.name
    if name == "sharding_constraint":
        return eqn.params.get("sharding")
    if name == "scan":
        idx = next(i for i, v in enumerate(eqn.outvars) if v is var)
        num_carry = eqn.params.get("num_carry", 0)
        if idx < num_carry:
            body = eqn.params["jaxpr"].jaxpr
            return constraint_for_output(body, body.outvars[idx])
    if name == "pjit":
        idx = next(i for i, v in enumerate(eqn.outvars) if v is var)
        body = eqn.params["jaxpr"].jaxpr
        return constraint_for_output(body, body.outvars[idx])
    return None


def walk_eqns(jaxpr):
    """Every eqn, recursing into sub-jaxprs (scan/pjit/cond bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from walk_eqns(sub)


def _sub_jaxprs(v):
    import jax.core as core
    if isinstance(v, core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, core.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for e in v:
            yield from _sub_jaxprs(e)


def has_sharding_constraint(jaxpr) -> bool:
    return any(e.primitive.name == "sharding_constraint"
               for e in walk_eqns(jaxpr))


def int8_dot_operands(jaxpr) -> list:
    """(eqn, operand-dtypes) for every dot_general with an int8 operand."""
    import numpy as np
    hits = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        dts = [getattr(v.aval, "dtype", None) for v in eqn.invars[:2]]
        if any(dt is not None and np.dtype(dt) == np.dtype("int8")
               for dt in dts):
            hits.append((eqn, dts))
    return hits


# -- output classification ----------------------------------------------------

def kv_out_leaves(key, out_sds) -> list:
    """[(flat_index, kv_name, aval)] for the KV-state leaves of a graph's
    output tree. Dict-keyed leaves classify by their final key; the dense
    splice returns bare ``(k, v)`` positionally."""
    import jax
    leaves = jax.tree_util.tree_flatten_with_path(out_sds)[0]
    if kind_of(key) == "dsplice":
        return [(i, ("k", "v")[i], leaf) for i, (_, leaf)
                in enumerate(leaves)]
    out = []
    for i, (path, leaf) in enumerate(leaves):
        last = path[-1] if path else None
        name = getattr(last, "key", None)
        if name in KV_NAMES:
            out.append((i, name, leaf))
    return out


def _spec_axes(spec) -> tuple:
    """Normalized per-dim axis tuples of a PartitionSpec (None-padded
    entries dropped from the tail)."""
    if spec is None:
        return ()
    norm = []
    for e in spec:
        if e is None:
            norm.append(())
        elif isinstance(e, (tuple, list)):
            norm.append(tuple(e))
        else:
            norm.append((e,))
    while norm and norm[-1] == ():
        norm.pop()
    return tuple(norm)


# -- per-job verification -----------------------------------------------------

def check_job(cell: Cell, cfg, policy, key, fn, args,
              compile_jobs: bool = True) -> list:
    import jax
    import jax.numpy as jnp

    findings: list[Finding] = []
    is_mesh = policy.mesh is not None
    kind = kind_of(key)

    traced = fn.trace(*args)
    jaxpr = traced.jaxpr.jaxpr           # the graph body
    # out_info is the output pytree of shape/dtype leaves from the SAME
    # trace — eval_shape here would re-trace the whole decoder per job
    out_sds = traced.out_info
    kv_outs = kv_out_leaves(key, out_sds)

    # ---- GRA002: constrain_kv on every KV output ----
    if is_mesh:
        for i, name, leaf in kv_outs:
            sharding = constraint_for_output(jaxpr, jaxpr.outvars[i])
            if sharding is None:
                findings.append(_f(
                    "GRA002", cell.name, key,
                    f"KV output `{name}` is not pinned by constrain_kv: "
                    "a donation round-trip may let GSPMD gather or "
                    "re-layout the pool mid-serve"))
                continue
            want = _spec_axes(policy.kv_spec(name, len(leaf.shape)))
            got = _spec_axes(getattr(sharding, "spec", None))
            if got != want:
                findings.append(_f(
                    "GRA002", cell.name, key,
                    f"KV output `{name}` constrained to {got}, policy "
                    f"declares {want}: the pool would resettle into a "
                    "different layout than admission/decode write through"))
    elif has_sharding_constraint(jaxpr):
        findings.append(_f(
            "GRA002", cell.name, key,
            "sharding_constraint in a SINGLE-DEVICE graph: the 1x1 "
            "policy must trace bit-identical graphs to the pre-split "
            "engine (identity hooks only)"))

    # ---- GRA003: donation declared ----
    # Traced.donate_argnums reports FLAT leaf indices; map each expected
    # top-level argument to its flat span
    import jax.tree_util as jtu
    counts = [len(jtu.tree_flatten(a)[0]) for a in args]
    starts = [sum(counts[:i]) for i in range(len(args))]
    donated = set(traced.donate_argnums or ())
    for argpos, what in EXPECTED_DONATION.get(kind, ()):
        span = set(range(starts[argpos], starts[argpos] + counts[argpos]))
        if not span <= donated:
            findings.append(_f(
                "GRA003", cell.name, key,
                f"{what} (arg {argpos}) is not donated: every window "
                "would copy the full buffer instead of aliasing it"))

    # ---- GRA004: dtype closure ----
    for eqn, dts in int8_dot_operands(jaxpr):
        findings.append(_f(
            "GRA004", cell.name, key,
            f"dot_general with int8 operand(s) {dts}: int8 storage "
            "reached a matmul undequantized — values are missing their "
            "scales"))
    # scratch/gather outputs stay model dtype; pool payload stays the
    # pool dtype (int8 under kv_quant — the write really quantized);
    # scale planes stay f32
    model_dt = jnp.dtype(cfg.dtype)
    pool_dt = jnp.dtype(jnp.int8) if cell.kv_quant else model_dt
    for i, name, leaf in kv_outs:
        dt = jnp.dtype(leaf.dtype)
        if name == "table":
            continue
        if name.endswith("_scale"):
            if dt != jnp.dtype(jnp.float32):
                findings.append(_f(
                    "GRA004", cell.name, key,
                    f"scale plane `{name}` left the graph as {dt}, "
                    "expected float32"))
            continue
        want = pool_dt if _leaf_is_pool(kind, out_sds, i) else model_dt
        if dt != want:
            findings.append(_f(
                "GRA004", cell.name, key,
                f"KV output `{name}` left the graph as {dt}, expected "
                f"{want} ({'pool storage' if want == pool_dt else 'model'}"
                " dtype) — the quant boundary leaked"))

    # ---- compiled-artifact checks ----
    if compile_jobs:
        compiled = traced.lower().compile()
        findings += _check_compiled(cell, policy, key, args, compiled,
                                    donated, kv_outs, out_sds)
    return findings


def _leaf_is_pool(kind: str, out_sds, flat_index: int) -> bool:
    """Whether KV output ``flat_index`` is POOL storage (carries the pool
    dtype — int8 under kv_quant) rather than scratch/dense-cache state
    (always the model dtype). Positional, by graph kind: splice returns
    the pool; chunkgroup returns (pool, scratch, last); decode/verify
    round-trip the engine cache (the pool in paged mode)."""
    import jax
    if kind in ("splice", "decode", "verify"):
        return True
    if kind == "chunkgroup":
        # output element 0 is the pool dict; find the flat span of it
        leaves0 = jax.tree_util.tree_flatten(out_sds[0])[0]
        return flat_index < len(leaves0)
    return False


def _check_compiled(cell, policy, key, args, compiled, donated, kv_outs,
                    out_sds) -> list:
    import jax
    findings: list[Finding] = []
    is_mesh = policy.mesh is not None

    # GRA003: every donated leaf genuinely aliased in the executable.
    # donate_argnums and input_output_alias live in DIFFERENT index
    # spaces: donation indexes the traced flat leaves, the alias map
    # indexes HLO entry parameters, and jit DROPS unused leaves from the
    # entry signature (keep_unused=False default) — so translate through
    # the executable's kept-variable set before comparing.
    donated_flat = set(donated)          # traced flat leaf indices
    aliased_params = _aliased_params(compiled.as_text())
    kept = _kept_var_idx(compiled)
    if kept is None:
        n_flat = sum(len(jax.tree_util.tree_flatten(a)[0]) for a in args)
        if _entry_param_count(compiled.as_text()) == n_flat:
            kept = list(range(n_flat))   # nothing dropped: identity map
    if kept is None:
        findings.append(_f(
            "GRA003", cell.name, key,
            "cannot verify donation aliasing: jit dropped unused "
            "argument leaves and the executable exposes no kept-variable "
            "mapping on this jax version — make every argument used or "
            "extend _kept_var_idx"))
    else:
        aliased_flat = {kept[p] for p in aliased_params
                        if p < len(kept)}
        for idx in sorted(donated_flat & set(kept) - aliased_flat):
            findings.append(_f(
                "GRA003", cell.name, key,
                f"donated input leaf {idx} is NOT aliased in the "
                "compiled executable (input_output_alias) — XLA dropped "
                "the donation (shape/dtype/layout mismatch with every "
                "output), so the round-trip silently copies the buffer "
                "every window"))

    if not is_mesh:
        return findings

    if kind_of(key) in PARAMS_KINDS:
        findings += _check_weight_shardings(cell, policy, key, args,
                                            compiled)

    # GRA002 (compiled face): pool payload outputs keep the head axis —
    # for EVERY mesh graph (the splice/gather plumbing round-trips the
    # pool without taking weights at all)
    import jax.tree_util as jtu
    out_sh = jtu.tree_flatten(compiled.output_shardings)[0]
    for i, name, leaf in kv_outs:
        if name == "table" or name.endswith("_scale"):
            continue
        want = _spec_axes(policy.kv_spec(name, len(leaf.shape)))
        if not want:
            continue
        got_sp = _spec_axes(getattr(out_sh[i], "spec", None))
        if got_sp != want:
            findings.append(_f(
                "GRA002", cell.name, key,
                f"compiled output sharding of `{name}` is {got_sp}, "
                f"policy pins {want}: GSPMD resettled the pool across "
                "the donation round-trip"))
    return findings


def _check_weight_shardings(cell, policy, key, args, compiled) -> list:
    """GRA001: weight leaves carry the policy's resolved specs
    end-to-end (declared-vs-resolved replication + compiled input
    shardings leaf-match). Mesh cells, params-taking graphs only."""
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    findings: list[Finding] = []
    params_sds = args[0]
    declared, resolved = policy.param_specs(params_sds)
    in_sh = compiled.input_shardings[0][0]   # the params arg subtree
    is_leaf = lambda x: isinstance(x, P)  # noqa: E731
    decl = jtu.tree_flatten_with_path(declared, is_leaf=is_leaf)[0]
    reso = jtu.tree_flatten(resolved, is_leaf=is_leaf)[0]
    got = jtu.tree_flatten(in_sh)[0]
    mesh_axes = {n for n, s in policy.mesh.shape.items() if s > 1}
    any_tp = False
    for (path, dspec), rspec, sh in zip(decl, reso, got):
        label = jtu.keystr(path)
        d_ax = {a for dim in _spec_axes(dspec) for a in dim}
        r_ax = {a for dim in _spec_axes(rspec) for a in dim}
        if "tp" in r_ax & mesh_axes:
            any_tp = True
        if d_ax & mesh_axes and not r_ax & mesh_axes:
            findings.append(_f(
                "GRA001", cell.name, key,
                f"weight leaf {label} declared {_spec_axes(dspec)} but "
                f"resolved REPLICATED (divisibility fallback): every "
                "chip holds the full tensor — all the HBM, none of the "
                "capacity"))
        actual = _spec_axes(getattr(sh, "spec", None))
        if actual != _spec_axes(rspec):
            findings.append(_f(
                "GRA001", cell.name, key,
                f"weight leaf {label} lowered with sharding {actual}, "
                f"policy resolved {_spec_axes(rspec)}: the executable "
                "will not run on the layout the policy places"))
    if "tp" in mesh_axes and not any_tp and decl:
        findings.append(_f(
            "GRA001", cell.name, key,
            "no tp-sharded weight leaf under tp>1: the decoder layout "
            "rule did not match this param tree — every matmul operand "
            "is replicated"))
    return findings


def _kept_var_idx(compiled):
    """Sorted kept-flat-leaf indices of a compiled executable (jit drops
    unused leaves from the HLO entry signature; HLO parameter N is flat
    leaf kept[N]). None when this jax version doesn't expose it."""
    ex = getattr(compiled, "_executable", None)
    kept = getattr(ex, "_kept_var_idx", getattr(ex, "kept_var_idx", None))
    if kept is None:
        return None
    return sorted(kept)


def _entry_param_count(hlo_text: str):
    """Number of entry parameters in a compiled HLO module, from the
    entry_computation_layout header; None when unparseable."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo_text,
                  re.DOTALL)
    if not m:
        return None
    body = m.group(1).strip()
    if not body:
        return 0
    depth, count = 0, 1
    for ch in body:                      # commas inside shapes don't
        if ch in "[{(":                  # separate parameters
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            count += 1
    return count


# -- the matrix runner --------------------------------------------------------

def signature_findings(cell_name: str, have: set, want: set) -> list:
    """GRA005: the precompiled signature set (``have`` — the factory's
    lowering_jobs keys) must equal the serve-loop-reachable set
    (``want`` — reachable_keys). Asymmetric messages: an unprecompiled
    reachable key is a mid-serve stall, a dead precompile is boot-time
    waste or a stale dispatch-site enumeration."""
    findings: list[Finding] = []
    for k in sorted(map(str, want - have)):
        findings.append(_f(
            "GRA005", cell_name, k,
            "signature reachable from the WindowScheduler but NOT "
            "precompiled: the first request hitting it stalls every "
            "stream behind a mid-serve XLA compile"))
    for k in sorted(map(str, have - want)):
        findings.append(_f(
            "GRA005", cell_name, k,
            "signature precompiled but not reachable from the serve "
            "loop: dead boot-time compile (or reachable_keys is stale — "
            "update the dispatch-site enumeration)"))
    return findings


def run_cell(cell: Cell, compile_jobs: bool = True) -> tuple:
    """(findings, stats) for one cell."""
    t0 = time.perf_counter()
    (cfg, ecfg, policy, factory, params, state, buckets,
     spec_lens) = build_cell(cell)
    jobs = list(factory.lowering_jobs(
        params, state["kv_cache"], state["pool"], state["scratch"],
        state["mb"], buckets, spec_lens, state["rng"]))

    # GRA005: the job keys ARE the precompile set; they must equal the
    # serve loop's reachable set exactly
    have = {k for k, _, _ in jobs}
    want = factory.reachable_keys(buckets, spec_lens)
    findings: list[Finding] = signature_findings(cell.name, have, want)

    for key, fn, args in jobs:
        findings.extend(check_job(cell, cfg, policy, key, fn, args,
                                  compile_jobs=compile_jobs))
    stats = {"cell": cell.name, "jobs": len(jobs),
             "elapsed_s": round(time.perf_counter() - t0, 3)}
    return findings, stats


def run_matrix(cells: Optional[list] = None,
               compile_jobs: bool = True) -> dict:
    """Run Pass A over the matrix. Returns ``{"findings": [...],
    "cells": [stats...], "elapsed_s": float}``."""
    t0 = time.perf_counter()
    cells = cells if cells is not None else list(MATRIX)
    findings: list[Finding] = []
    stats = []
    for cell in cells:
        f, s = run_cell(cell, compile_jobs=compile_jobs)
        findings.extend(f)
        stats.append(s)
    return {"findings": findings, "cells": stats,
            "elapsed_s": round(time.perf_counter() - t0, 3)}


def device_guard(min_devices: int = 8) -> Optional[str]:
    """None when the forced CPU mesh is usable; otherwise the loud
    skip-with-recipe string (mirrors the multichip conftest marker: a
    caller-pinned XLA_FLAGS wins over our forcing, and silently passing
    with 1 device would claim coverage that never ran)."""
    import jax
    n = jax.device_count()
    if n >= min_devices:
        return None
    return (f"graphcheck needs {min_devices} virtual CPU devices for the "
            f"topology matrix, have {n} — re-run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 (or "
            "unset XLA_FLAGS and let the graphcheck CLI force it)")
