"""Pass B — AST rules for the sharding/dtype boundary (ISSUE 11).

- **SHD001** ``jax.jit`` opened in a mesh-capable serving module that is
  not a declared jit owner (``[graphcheck] jit_owners`` in
  boundaries.toml) and carries no explicit ``out_shardings``. The engine
  split made ``serving/graphs.py`` the ONLY serving module that traces
  jax; a drive-by jit elsewhere bypasses the sharding policy, the
  executable cache and the recompile sentinel at once.
- **SHD002** use of a donated buffer after the donating call: a name
  bound from ``jax.jit(..., donate_argnums=...)`` is called, and an
  argument passed at a donated position is read again afterwards without
  being rebound. The donated buffer is DEAD after the call — XLA may
  have reused its pages — so that read returns garbage on hardware while
  silently "working" on backends that ignore donation.
- **DTY001** raw int8 KV symbols (``[graphcheck] int8_symbols``, e.g.
  ``quantize_kv``/``dequantize_kv``) imported from ``ops.quant`` by a
  module outside the declared carrier list (``int8_carriers``). This is
  the static face of the BND001 restricted list, one level finer: BND001
  bounds who may import ``tpu9.ops.quant`` at all; DTY001 bounds which
  of those modules may touch the raw int8 payload/scale layout, so the
  dtype-closure invariant Pass A checks per-graph also holds at the
  import graph.

All three are configured from the ``[graphcheck]`` table in
boundaries.toml so scope changes are reviewed edits there, not code
changes here.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ..findings import Finding
from ..rules import dotted_name

GRAPH_AST_RULES = ("SHD001", "SHD002", "DTY001")

# Defaults mirror boundaries.toml's [graphcheck] table; the toml wins
# when present so the contract stays a reviewed, declarative edit.
DEFAULT_GRAPH_CFG = {
    "mesh_scope": ["tpu9/serving/"],
    "jit_owners": ["tpu9/serving/graphs.py", "tpu9/serving/shard/policy.py"],
    "int8_sources": ["tpu9.ops.quant"],
    "int8_symbols": ["quantize_kv", "dequantize_kv"],
    "int8_carriers": ["tpu9.ops", "tpu9.models.transformer",
                      "tpu9.serving.graphs"],
}


@dataclass
class GraphLintConfig:
    mesh_scope: list = field(
        default_factory=lambda: list(DEFAULT_GRAPH_CFG["mesh_scope"]))
    jit_owners: list = field(
        default_factory=lambda: list(DEFAULT_GRAPH_CFG["jit_owners"]))
    int8_sources: list = field(
        default_factory=lambda: list(DEFAULT_GRAPH_CFG["int8_sources"]))
    int8_symbols: list = field(
        default_factory=lambda: list(DEFAULT_GRAPH_CFG["int8_symbols"]))
    int8_carriers: list = field(
        default_factory=lambda: list(DEFAULT_GRAPH_CFG["int8_carriers"]))

    @classmethod
    def from_dict(cls, raw: dict) -> "GraphLintConfig":
        cfg = cls()
        for key in DEFAULT_GRAPH_CFG:
            if key in raw:
                setattr(cfg, key, list(raw[key]))
        return cfg


def _in_scope(path: str, prefixes) -> bool:
    return any(path == p.rstrip("/") or path.startswith(p)
               for p in prefixes)


def _module_prefix(mod: str, prefixes) -> bool:
    return any(mod == p or mod.startswith(p + ".") for p in prefixes)


# -- SHD001 -------------------------------------------------------------------

def _check_jit_ownership(path: str, tree: ast.AST,
                         cfg: GraphLintConfig) -> list[Finding]:
    if not _in_scope(path, cfg.mesh_scope) or path in cfg.jit_owners:
        return []
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name not in ("jax.jit", "jit"):
            continue
        if any(kw.arg == "out_shardings" for kw in node.keywords):
            # an explicit layout contract is the one sanctioned reason to
            # jit outside the factory (the policy's sharded-zeros builder)
            continue
        findings.append(Finding(
            "SHD001", path, node.lineno, node.col_offset,
            f"`{name}` opened outside the GraphFactory (declared jit "
            f"owners: {cfg.jit_owners}) without explicit out_shardings: "
            "serving graphs must trace through serving/graphs.py so the "
            "sharding policy, executable cache and recompile sentinel "
            "all apply", symbol=name))
    return findings


# -- SHD002 -------------------------------------------------------------------

def _donated_positions(call: ast.Call):
    """Literal donate_argnums of a ``jax.jit(...)`` call, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None          # non-literal: can't reason
                out.append(e.value)
            return tuple(out)
        return None
    return None


def _check_donated_reuse(path: str, tree: ast.AST) -> list[Finding]:
    """Per-scope linear scan: find names bound from donating jits, then
    flag any read of a buffer passed at a donated position after the
    donating call, unless the name was rebound in between (including by
    the call's own result assignment, the round-trip idiom)."""
    findings: list[Finding] = []

    def scan_scope(owner: ast.AST) -> None:
        # nested function bodies are their own scopes
        nested: set[int] = set()
        for c in ast.walk(owner):
            if (isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)) and c is not owner
                    and id(c) not in nested):
                nested.update(id(x) for x in ast.walk(c))
        own = [n for n in ast.walk(owner) if id(n) not in nested]

        jits: dict[str, tuple] = {}
        for n in own:
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)
                    and dotted_name(n.value.func) in ("jax.jit", "jit")):
                continue
            donated = _donated_positions(n.value)
            if donated is None:
                continue
            for tgt in n.targets:
                tname = dotted_name(tgt)
                if tname:
                    jits[tname] = donated
        if not jits:
            return

        pos = lambda n: (n.lineno, n.col_offset)  # noqa: E731
        # result-target names per donating call: `tok, kv = f(...)`
        # rebinds kv AFTER the RHS runs, even though the target's
        # position precedes the call's
        result_names: dict[int, set] = {}
        for n in own:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                names = set()
                for tgt in n.targets:
                    for sub in ast.walk(tgt):
                        nm = dotted_name(sub)
                        if nm:
                            names.add(nm)
                result_names[id(n.value)] = names

        # (position, donating-call node, buffer name) per donated arg
        dead: list[tuple] = []
        stores: list[tuple] = []
        loads: list[tuple] = []
        for n in own:
            if isinstance(n, ast.Call):
                fname = dotted_name(n.func)
                if fname in jits:
                    inside = {id(x) for x in ast.walk(n)}
                    for i in jits[fname]:
                        if i < len(n.args):
                            buf = dotted_name(n.args[i])
                            if buf:
                                dead.append((pos(n), n, buf, inside))
            if isinstance(n, (ast.Name, ast.Attribute)):
                nm = dotted_name(n)
                if not nm:
                    continue
                ctx = getattr(n, "ctx", None)
                if isinstance(ctx, ast.Store):
                    stores.append((pos(n), nm))
                elif isinstance(ctx, ast.Load):
                    loads.append((pos(n), nm, id(n)))

        for dpos, call, buf, inside in dead:
            if buf in result_names.get(id(call), ()):
                continue                 # round-trip idiom: rebound by
                                         # the donating call's own result
            rebinds = [p for p, nm in stores if nm == buf and p > dpos]
            for lpos, nm, nid in sorted(loads):
                if nm != buf or lpos <= dpos or nid in inside:
                    continue
                if any(rp <= lpos for rp in rebinds):
                    break                # rebound: later reads are fine
                findings.append(Finding(
                    "SHD002", path, lpos[0], lpos[1],
                    f"`{buf}` is read after being DONATED to "
                    f"`{dotted_name(call.func)}` (line {dpos[0]}): the "
                    "buffer is dead after the call — XLA may alias its "
                    "pages into the output — so this read returns "
                    "garbage on hardware; rebind the name from the "
                    "call's result (the round-trip idiom) or drop the "
                    "donation", symbol=buf))
                break                    # one finding per donated buffer
    scan_scope(tree)
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(n)
    return findings


# -- DTY001 -------------------------------------------------------------------

def _check_int8_escape(path: str, tree: ast.AST,
                       cfg: GraphLintConfig) -> list[Finding]:
    mod = path[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    if _module_prefix(mod, cfg.int8_carriers):
        return []
    findings: list[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.ImportFrom):
            continue
        if n.level:
            parts = mod.split(".")
            if not path.endswith("__init__.py"):
                parts = parts[:-1]
            parts = parts[: len(parts) - n.level + 1]
            base = ".".join(parts)
            target = f"{base}.{n.module}" if n.module else base
        else:
            target = n.module or ""
        if target not in cfg.int8_sources:
            continue
        for a in n.names:
            if a.name in cfg.int8_symbols:
                findings.append(Finding(
                    "DTY001", path, n.lineno, n.col_offset,
                    f"`{mod}` imports raw int8 KV symbol `{a.name}` from "
                    f"`{target}`: only the declared int8 carriers "
                    f"{cfg.int8_carriers} (boundaries.toml [graphcheck]) "
                    "may touch the payload/scale layout — everything "
                    "else must see KV through the dequantizing readers",
                    symbol=a.name))
    return findings


def check_graph_file(path: str, tree: ast.AST,
                     cfg: GraphLintConfig | None = None) -> list[Finding]:
    cfg = cfg or GraphLintConfig()
    findings = _check_jit_ownership(path, tree, cfg)
    findings += _check_donated_reuse(path, tree)
    findings += _check_int8_escape(path, tree, cfg)
    return findings
