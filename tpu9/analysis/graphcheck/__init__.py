"""tpu9 graphcheck — static verification of sharding, dtype, and donation
invariants in the traced serving graphs (ISSUE 11).

Two passes:

- **Pass A (abstract lowering)** — ``passes.py``: for each preset ×
  topology cell in the declared matrix (``matrix.py``), drive
  ``GraphFactory.lowering_jobs`` on a forced CPU mesh and verify, from
  the jaxpr and the compiled artifact, the invariants the multichip
  engine split depends on: weights carry their ``MeshPolicy``
  PartitionSpecs (GRA001), every KV-pool output is pinned by
  ``constrain_kv`` with the head-axis spec (GRA002), donated buffers are
  genuinely aliased in the compiled executable (GRA003), int8 storage
  never reaches a matmul undequantized and scratch stays the model dtype
  (GRA004), and the executable-cache signature set is closed — the keys
  the serve loop can request equal the precompile set, so steady-state
  serving provably cannot recompile (GRA005).

- **Pass B (AST rules)** — ``astrules.py``: tpu9lint rules SHD001
  (``jax.jit`` outside the GraphFactory in mesh-capable serving modules),
  SHD002 (use of a donated buffer after the donating call) and DTY001
  (raw int8 KV symbols imported outside the declared carrier modules).
  These run inside ``python -m tpu9.analysis`` with the normal
  suppression/baseline machinery, and again under the graphcheck CLI.

Run it:

    python -m tpu9.analysis.graphcheck              # full matrix + Pass B
    python -m tpu9.analysis.graphcheck --cell llama3-8b@2x1
    python -m tpu9.analysis.graphcheck --format json

``scripts/graph_gate.py`` is the tier-1 wiring (budgeted, loud skip with
a re-run recipe when the forced 8-device CPU mesh is unavailable).

This module stays import-light (no jax): the lint runner imports Pass B
from here on every lint run; Pass A's jax machinery loads only when a
matrix actually runs.
"""

from .astrules import GRAPH_AST_RULES, check_graph_file

__all__ = ["GRAPH_AST_RULES", "check_graph_file"]
