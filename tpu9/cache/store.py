"""Content-addressed disk store with LRU eviction.

Layout: ``<root>/aa/<sha256>`` (2-hex fan-out). Eviction walks by access
time once usage crosses ``max_bytes`` (reference: pkg/cache/storage.go:71 +
storage_eviction.go).
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import tempfile
import time
from typing import Optional


def chunk_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class DiskStore:
    def __init__(self, root: str, max_bytes: int = 32 * 1024 ** 3):
        self.root = root
        self.max_bytes = max_bytes
        os.makedirs(root, exist_ok=True)
        self._used = 0
        self._scan_usage()
        self._lock = asyncio.Lock()
        self.stats = {"hits": 0, "misses": 0, "puts": 0, "evictions": 0}

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, digest[:2], digest)

    def _scan_usage(self) -> None:
        total = 0
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for fn in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        self._used = total

    @property
    def used_bytes(self) -> int:
        return self._used

    def has(self, digest: str) -> bool:
        return os.path.exists(self._path(digest))

    def get_path(self, digest: str) -> Optional[str]:
        """Path for zero-copy reads (sendfile/hardlink); touches atime."""
        p = self._path(digest)
        if not os.path.exists(p):
            self.stats["misses"] += 1
            return None
        now = time.time()
        try:
            os.utime(p, (now, os.path.getmtime(p)))
        except OSError:
            pass
        self.stats["hits"] += 1
        return p

    async def get(self, digest: str) -> Optional[bytes]:
        p = self.get_path(digest)
        if p is None:
            return None

        def read() -> Optional[bytes]:
            try:
                with open(p, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                # LRU eviction raced between the existence check and this
                # open: a miss, not an error — the client falls through to
                # peers/source instead of failing the whole restore
                return None

        return await asyncio.to_thread(read)

    async def put(self, data: bytes, digest: str = "") -> str:
        digest = digest or chunk_hash(data)
        p = self._path(digest)
        if os.path.exists(p):
            return digest
        os.makedirs(os.path.dirname(p), exist_ok=True)

        def write() -> bool:
            # atomic publish: tmp + rename so concurrent readers never see a
            # partial chunk (reference guards this with mount locks).
            # Returns whether WE published a new file — N concurrent puts
            # of the same digest must account its bytes once, or _used
            # drifts upward and eviction starts thrashing hot chunks.
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p))
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                existed = os.path.exists(p)
                os.rename(tmp, p)
                return not existed
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        if await asyncio.to_thread(write):
            self._used += len(data)
        self.stats["puts"] += 1
        if self._used > self.max_bytes:
            async with self._lock:
                await asyncio.to_thread(self._evict)
        return digest

    def _evict(self) -> None:
        """Drop least-recently-accessed chunks to 90% of budget."""
        entries = []
        for dirpath, _d, filenames in os.walk(self.root):
            for fn in filenames:
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                    entries.append((st.st_atime, st.st_size, p))
                except OSError:
                    pass
        entries.sort()
        target = int(self.max_bytes * 0.9)
        for _atime, size, p in entries:
            if self._used <= target:
                break
            try:
                os.unlink(p)
                self._used -= size
                self.stats["evictions"] += 1
            except OSError:
                pass
