"""Chunk server: raw-TCP data plane over a DiskStore.

Protocol (shares the state-bus framing): request frame
``{"op": "get"|"put"|"has"|"stats"|"groups", "hash": ..., "len": n}``; for
``put`` the
raw chunk bytes follow the header frame; ``get`` replies
``{"ok": true, "len": n}`` then n raw bytes (zero-copy from the store file
via loop.sendfile when the transport supports it — the reference uses
sendfile(2) the same way, pkg/cache/sendfile_linux.go).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from ..statestore import wire
from .store import DiskStore, chunk_hash

log = logging.getLogger("tpu9.cache")

MAX_CHUNK = 64 * 1024 * 1024


class ChunkServer:
    def __init__(self, store: DiskStore, host: str = "127.0.0.1",
                 port: int = 0, groups_fn=None):
        self.store = store
        self.host = host
        self.port = port
        # scale-out plane (ISSUE 17): () -> sequence of complete shard
        # group content keys this replica can re-serve — the worker wires
        # its CacheClient's advertisement set in; joining peers (and the
        # bench) ask over the wire with op "groups"
        self.groups_fn = groups_fn
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set[asyncio.StreamWriter] = set()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> "ChunkServer":
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # peers hold PERSISTENT connections (CacheClient._conns), and
            # Server.wait_closed (≥3.12.1) waits for every live handler —
            # stopping a worker must not deadlock on another live worker's
            # idle connection, so sever them first
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(),
                                       timeout=5.0)
            except asyncio.TimeoutError:
                log.warning("chunk server close timed out with "
                            "%d connections", len(self._conns))
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                try:
                    req = await wire.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                op = req.get("op")
                if op == "get":
                    await self._serve_get(req.get("hash", ""), writer)
                elif op == "put":
                    n = int(req.get("len", 0))
                    if n > MAX_CHUNK:
                        writer.write(wire.pack({"ok": False,
                                                "error": "chunk too large"}))
                        await writer.drain()
                        break
                    data = await reader.readexactly(n)
                    computed = chunk_hash(data)
                    claimed = req.get("hash")
                    if claimed and claimed != computed:
                        # NEVER store a digest→data mismatch: a poisoned
                        # entry would be served as a verification-free
                        # "local hit" to every later consumer
                        writer.write(wire.pack({"ok": False,
                                                "error": "digest mismatch"}))
                        await writer.drain()
                        continue
                    digest = await self.store.put(data, computed)
                    writer.write(wire.pack({"ok": True, "hash": digest}))
                elif op == "has":
                    writer.write(wire.pack({"ok": True,
                                            "has": self.store.has(
                                                req.get("hash", ""))}))
                elif op == "stats":
                    writer.write(wire.pack({"ok": True,
                                            "used": self.store.used_bytes,
                                            **self.store.stats}))
                elif op == "groups":
                    try:
                        groups = sorted(self.groups_fn()) \
                            if self.groups_fn else []
                    except Exception:   # noqa: BLE001 — advertisement is
                        groups = []     # best-effort, never a wire error
                    writer.write(wire.pack({"ok": True, "groups": groups}))
                else:
                    writer.write(wire.pack({"ok": False,
                                            "error": f"bad op {op!r}"}))
                await writer.drain()
        finally:
            self._conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _serve_get(self, digest: str,
                         writer: asyncio.StreamWriter) -> None:
        path = self.store.get_path(digest)
        if path is None:
            writer.write(wire.pack({"ok": False, "error": "not found"}))
            return
        try:
            size = os.path.getsize(path)
        except OSError:
            # eviction raced the existence check: a miss, not a dropped
            # connection
            writer.write(wire.pack({"ok": False, "error": "not found"}))
            return
        writer.write(wire.pack({"ok": True, "len": size}))
        await writer.drain()
        loop = asyncio.get_running_loop()
        transport = writer.transport
        try:
            with open(path, "rb") as f:  # tpu9: noqa[ASY004] metadata-only open; the bytes move via loop.sendfile (async, zero-copy)
                await loop.sendfile(transport, f, fallback=True)
        except (NotImplementedError, AttributeError, RuntimeError):
            # transport without sendfile: stream manually
            with open(path, "rb") as f:  # tpu9: noqa[ASY004] metadata-only open; 1 MiB reads interleave with awaited drains below
                while True:
                    block = f.read(1 << 20)
                    if not block:
                        break
                    writer.write(block)
                    await writer.drain()
