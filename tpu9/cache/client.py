"""Cache client with rendezvous (HRW) routing.

Reference analogue: ``pkg/cache/client.go:187,272`` — highest-random-weight
hashing over discovered hosts picks the canonical holder for each chunk;
reads try local disk, then the HRW-ordered peers, then the source of truth;
writes land locally and on the primary peer. Peer discovery is injected (the
worker registry advertises cache addresses), so the client is transport-pure
and unit-testable.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Awaitable, Callable, Optional, Sequence

from ..statestore import wire
from .store import DiskStore, chunk_hash

log = logging.getLogger("tpu9.cache")

# async () -> list of peer addresses ("host:port")
PeerFn = Callable[[], Awaitable[Sequence[str]]]
# async (hash) -> bytes | None — source of truth (registry dir, GCS, ...)
SourceFn = Callable[[str], Awaitable[Optional[bytes]]]


def hrw_order(digest: str, peers: Sequence[str]) -> list[str]:
    """Peers ordered by highest-random-weight for this chunk."""
    def weight(peer: str) -> int:
        return int.from_bytes(
            hashlib.sha256(f"{digest}|{peer}".encode()).digest()[:8], "big")

    return sorted(peers, key=weight, reverse=True)


class CacheClient:
    def __init__(self, store: DiskStore, peers: PeerFn,
                 source: Optional[SourceFn] = None,
                 self_address: str = "", replicas: int = 1,
                 connect_timeout: float = 2.0):
        self.store = store
        self.peers = peers
        self.source = source
        self.self_address = self_address
        self.replicas = replicas
        self.connect_timeout = connect_timeout
        self._conns: dict[str, tuple[asyncio.StreamReader,
                                     asyncio.StreamWriter]] = {}
        self._conn_locks: dict[str, asyncio.Lock] = {}
        self.stats = {"local_hits": 0, "peer_hits": 0, "source_fetches": 0,
                      "peer_errors": 0}

    async def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()

    # -- wire ---------------------------------------------------------------

    async def _conn(self, peer: str):
        entry = self._conns.get(peer)
        if entry is not None and not entry[1].is_closing():
            return entry
        host, _, port = peer.rpartition(":")
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), self.connect_timeout)
        self._conns[peer] = (reader, writer)
        return reader, writer

    # bound on the WHOLE request/response exchange with a peer: an
    # established-but-dead connection (peer host hung) would otherwise
    # block read_frame forever, pin the per-peer lock, and hang every
    # restore routed through that peer instead of falling to the source
    IO_TIMEOUT_S = 30.0

    async def _peer_get(self, peer: str, digest: str) -> Optional[bytes]:
        lock = self._conn_locks.setdefault(peer, asyncio.Lock())
        async with lock:
            try:
                return await asyncio.wait_for(
                    self._peer_get_io(peer, digest), self.IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as exc:
                self.stats["peer_errors"] += 1
                self._drop_conn(peer)
                log.debug("peer %s get failed: %s", peer, exc)
                return None

    async def _peer_get_io(self, peer: str, digest: str) -> Optional[bytes]:
        reader, writer = await self._conn(peer)
        writer.write(wire.pack({"op": "get", "hash": digest}))
        await writer.drain()
        head = await wire.read_frame(reader)
        if not head.get("ok"):
            return None
        return await reader.readexactly(int(head["len"]))

    def _drop_conn(self, peer: str) -> None:
        entry = self._conns.pop(peer, None)
        if entry is not None:
            try:
                entry[1].close()
            except Exception:   # noqa: BLE001 — already dead
                pass

    async def _peer_put(self, peer: str, digest: str, data: bytes) -> bool:
        lock = self._conn_locks.setdefault(peer, asyncio.Lock())
        async with lock:
            try:
                return await asyncio.wait_for(
                    self._peer_put_io(peer, digest, data),
                    self.IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError):
                self.stats["peer_errors"] += 1
                self._drop_conn(peer)
                return False

    async def _peer_put_io(self, peer: str, digest: str,
                           data: bytes) -> bool:
        reader, writer = await self._conn(peer)
        writer.write(wire.pack({"op": "put", "hash": digest,
                                "len": len(data)}))
        writer.write(data)
        await writer.drain()
        head = await wire.read_frame(reader)
        return bool(head.get("ok"))

    # -- public API ---------------------------------------------------------

    async def get(self, digest: str) -> Optional[bytes]:
        """local → HRW peers → source (populating local + primary)."""
        data = await self.store.get(digest)
        if data is not None:
            self.stats["local_hits"] += 1
            return data

        peers = [p for p in await self.peers() if p != self.self_address]
        for peer in hrw_order(digest, peers)[: max(self.replicas, 1) + 1]:
            data = await self._peer_get(peer, digest)
            if data is not None and chunk_hash(data) == digest:
                self.stats["peer_hits"] += 1
                await self.store.put(data, digest)
                return data

        if self.source is not None:
            data = await self.source(digest)
            if data is not None:
                self.stats["source_fetches"] += 1
                await self.store.put(data, digest)
                # seed the canonical holder so the next reader hits a peer
                ordered = hrw_order(digest, peers)
                if ordered:
                    asyncio.create_task(self._peer_put(ordered[0], digest,
                                                       data))
                return data
        return None

    async def put(self, data: bytes, digest: str = "") -> str:
        digest = digest or chunk_hash(data)
        await self.store.put(data, digest)
        peers = [p for p in await self.peers() if p != self.self_address]
        ordered = hrw_order(digest, peers)[: self.replicas]
        for peer in ordered:
            await self._peer_put(peer, digest, data)
        return digest

    async def get_many(self, digests: Sequence[str],
                       max_parallel: int = 8) -> dict[str, Optional[bytes]]:
        """Parallel fetch with bounded concurrency (prefetch window —
        reference prefetcher.go:49)."""
        sem = asyncio.Semaphore(max_parallel)
        out: dict[str, Optional[bytes]] = {}

        async def one(d: str) -> None:
            async with sem:
                out[d] = await self.get(d)

        await asyncio.gather(*[one(d) for d in dict.fromkeys(digests)])
        return out
