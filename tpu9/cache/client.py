"""Cache client with rendezvous (HRW) routing and hedged peer reads.

Reference analogue: ``pkg/cache/client.go:187,272`` — highest-random-weight
hashing over discovered hosts picks the canonical holder for each chunk;
reads try local disk, then the HRW-ordered peers, then the source of truth;
writes land locally and on the replica peers. Peer discovery is injected (the
worker registry advertises cache addresses), so the client is transport-pure
and unit-testable.

Peer reads are *hedged* (λScale-style tail cutting, arXiv:2502.09922): the
primary HRW holder gets a short head start (``hedge_delay_s``), then the
next-ranked peer is raced against it and the first *hash-verified* result
wins; the loser is cancelled and its connection dropped so a half-read
response can never poison the persistent per-peer stream. A slow or dead
primary therefore costs ~25 ms, not a full IO timeout, on the restore path.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import AsyncIterator, Awaitable, Callable, Optional, Sequence

from ..statestore import wire
from .store import DiskStore, chunk_hash

log = logging.getLogger("tpu9.cache")

# async () -> list of peer addresses ("host:port")
PeerFn = Callable[[], Awaitable[Sequence[str]]]
# async (hash) -> bytes | None — source of truth (registry dir, GCS, ...)
SourceFn = Callable[[str], Awaitable[Optional[bytes]]]


def hrw_order(digest: str, peers: Sequence[str]) -> list[str]:
    """Peers ordered by highest-random-weight for this chunk."""
    def weight(peer: str) -> int:
        return int.from_bytes(
            hashlib.sha256(f"{digest}|{peer}".encode()).digest()[:8], "big")

    return sorted(peers, key=weight, reverse=True)


class CacheClient:
    def __init__(self, store: DiskStore, peers: PeerFn,
                 source: Optional[SourceFn] = None,
                 self_address: str = "", replicas: int = 1,
                 connect_timeout: float = 2.0,
                 hedge_delay_s: float = 0.025):
        self.store = store
        self.peers = peers
        self.source = source
        self.self_address = self_address
        self.replicas = replicas
        self.connect_timeout = connect_timeout
        # head start the best-ranked peer gets before the next one is raced
        # against it; < 0 disables hedging (strictly sequential tries).
        # The effective delay adapts upward to ~2x the observed exchange
        # time (EWMA) — a healthy 4 MiB transfer on a slow link must not
        # trip a hedge on every chunk and double cache traffic; only
        # stragglers relative to this client's own history do.
        self.hedge_delay_s = hedge_delay_s
        # global EWMA is the COLD PRIOR only: the adaptive hedge delay for
        # a peer we have exchanged with uses that peer's own history — one
        # slow peer must not inflate the delay applied to fast peers
        # (ISSUE 13 satellite; the global kept a fleet-wide average that
        # did exactly that)
        self._peer_lat_ewma = 0.0
        self._peer_lat: dict[str, float] = {}
        # per-peer accounting surfaced by snapshot(): exchange counts,
        # bytes, errors and a fixed log-scale latency histogram. Plain
        # dict/list math only — the per-chunk hot path must not grow a
        # registry dependency (the worker heartbeat publishes gauges).
        self._peer_stats: dict[str, dict] = {}
        self._conns: dict[str, tuple[asyncio.StreamReader,
                                     asyncio.StreamWriter]] = {}
        self._conn_locks: dict[str, asyncio.Lock] = {}
        # fire-and-forget work (source→primary seeding): a bare create_task
        # holds no strong reference, so the event loop may GC the task
        # mid-flight — the set keeps it alive and close() drains it
        self._bg_tasks: set[asyncio.Task] = set()
        # scale-out plane (ISSUE 17): content keys of COMPLETE shard
        # groups this cache can re-serve to joining peers. The restore
        # path advertises a group only once its last shard landed — a
        # half-consumed group must never become a tree parent.
        self.groups: set[str] = set()
        self.stats = {"local_hits": 0, "peer_hits": 0, "source_fetches": 0,
                      "peer_errors": 0, "hedged_reads": 0, "hedge_wins": 0,
                      "hedge_wasted_bytes": 0, "bytes_local": 0,
                      "bytes_peer": 0, "bytes_source": 0,
                      # kv: namespace (ISSUE 16) — shipped KV-block
                      # payload traffic, split out from weight chunks so
                      # the cache-plane evidence can tell a restore storm
                      # from a migration storm
                      "kv_puts": 0, "kv_gets": 0, "kv_misses": 0,
                      "kv_bytes_put": 0, "kv_bytes_get": 0}
        # fault-injection plane (ISSUE 15): env-gated, None in production
        # — peer_read_error / peer_read_slow hooks in _peer_get exercise
        # the hedged-read + failover machinery deterministically
        self._faults = None
        from ..config import env_faults_spec
        if env_faults_spec():
            from ..testing.faults import FaultPlane
            self._faults = FaultPlane.from_env()

    def _spawn_bg(self, coro) -> asyncio.Task:
        task = asyncio.create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def close(self) -> None:
        for task in list(self._bg_tasks):
            task.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        self._bg_tasks.clear()
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()

    # -- wire ---------------------------------------------------------------

    async def _conn(self, peer: str):
        entry = self._conns.get(peer)
        if entry is not None and not entry[1].is_closing():
            return entry
        host, _, port = peer.rpartition(":")
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), self.connect_timeout)
        self._conns[peer] = (reader, writer)
        return reader, writer

    # bound on the WHOLE request/response exchange with a peer: an
    # established-but-dead connection (peer host hung) would otherwise
    # block read_frame forever, pin the per-peer lock, and hang every
    # restore routed through that peer instead of falling to the source
    IO_TIMEOUT_S = 30.0

    async def _peer_get(self, peer: str, digest: str) -> Optional[bytes]:
        if self._faults is not None:
            delay = self._faults.delay_s("peer_read_slow")
            if delay > 0:
                await asyncio.sleep(delay)
            if self._faults.fire("peer_read_error"):
                self.stats["peer_errors"] += 1
                self._peer_entry(peer)["errors"] += 1
                log.debug("fault plane: induced peer read error (%s)", peer)
                return None
            # tree_peer_loss (ISSUE 17): kill reads against ONE peer —
            # the tree parent — mid-transfer; the hedged read falls
            # through the surviving preference list, which IS the
            # worker-side re-plan the chaos leg proves
            if self._faults.fire_peer("tree_peer_loss", peer):
                self.stats["peer_errors"] += 1
                self._peer_entry(peer)["errors"] += 1
                self._drop_conn(peer)
                log.debug("fault plane: induced tree peer loss (%s)", peer)
                return None
        lock = self._conn_locks.setdefault(peer, asyncio.Lock())
        async with lock:
            try:
                return await asyncio.wait_for(
                    self._peer_get_io(peer, digest), self.IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as exc:
                self.stats["peer_errors"] += 1
                self._peer_entry(peer)["errors"] += 1
                self._drop_conn(peer)
                log.debug("peer %s get failed: %s", peer, exc)
                return None
            except asyncio.CancelledError:
                # hedge loser: the request may be mid-exchange — a reused
                # connection would serve the NEXT caller this response's
                # leftover bytes. Drop it so the stream is never dirty.
                self._drop_conn(peer)
                raise

    async def _peer_get_io(self, peer: str, digest: str) -> Optional[bytes]:
        reader, writer = await self._conn(peer)
        writer.write(wire.pack({"op": "get", "hash": digest}))
        await writer.drain()
        head = await wire.read_frame(reader)
        if not head.get("ok"):
            return None
        return await reader.readexactly(int(head["len"]))

    def _drop_conn(self, peer: str) -> None:
        entry = self._conns.pop(peer, None)
        if entry is not None:
            try:
                entry[1].close()
            except Exception:   # noqa: BLE001 — already dead
                pass

    async def _peer_put(self, peer: str, digest: str, data: bytes) -> bool:
        lock = self._conn_locks.setdefault(peer, asyncio.Lock())
        async with lock:
            try:
                return await asyncio.wait_for(
                    self._peer_put_io(peer, digest, data),
                    self.IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError):
                self.stats["peer_errors"] += 1
                self._drop_conn(peer)
                return False
            except asyncio.CancelledError:
                # same discipline as _peer_get: a put cancelled mid-frame
                # (parallel replica puts under a cancelled caller) must not
                # leave half a request on a pooled connection
                self._drop_conn(peer)
                raise

    async def _peer_put_io(self, peer: str, digest: str,
                           data: bytes) -> bool:
        reader, writer = await self._conn(peer)
        writer.write(wire.pack({"op": "put", "hash": digest,
                                "len": len(data)}))
        writer.write(data)
        await writer.drain()
        head = await wire.read_frame(reader)
        return bool(head.get("ok"))

    # -- accounting ---------------------------------------------------------

    # log-scale exchange-latency buckets (upper edges, seconds); the last
    # bucket is the +Inf overflow — small enough to ship on every worker
    # heartbeat, detailed enough to see a peer fall off a cliff
    LAT_BUCKETS_S = (0.001, 0.005, 0.025, 0.1, 0.5, 2.0)

    def _peer_entry(self, peer: str) -> dict:
        entry = self._peer_stats.get(peer)
        if entry is None:
            entry = self._peer_stats[peer] = {
                "exchanges": 0, "bytes": 0, "errors": 0, "total_s": 0.0,
                "hist": [0] * (len(self.LAT_BUCKETS_S) + 1)}
        return entry

    def _note_exchange(self, peer: str, dt: float, nbytes: int) -> None:
        """One verified peer exchange: per-peer EWMA + histogram + bytes.
        This is the hook ``bench.py --phase obs`` prices (µs-scale dict
        math per multi-MiB chunk)."""
        prior = self._peer_lat.get(peer)
        self._peer_lat[peer] = dt if prior is None \
            else 0.2 * dt + 0.8 * prior
        self._peer_lat_ewma = dt if self._peer_lat_ewma == 0.0 \
            else 0.2 * dt + 0.8 * self._peer_lat_ewma
        entry = self._peer_entry(peer)
        entry["exchanges"] += 1
        entry["bytes"] += nbytes
        entry["total_s"] += dt
        for i, edge in enumerate(self.LAT_BUCKETS_S):
            if dt <= edge:
                entry["hist"][i] += 1
                break
        else:
            entry["hist"][-1] += 1

    def _lat_estimate(self, peer: str) -> float:
        """This peer's own EWMA, falling back to the global cold prior for
        a peer we have never exchanged with."""
        return self._peer_lat.get(peer) or self._peer_lat_ewma

    @staticmethod
    def _tally(ledger: Optional[dict], key: str, n: int = 1) -> None:
        """Per-CALL accounting sink: ``get``/``get_stream`` callers that
        need traffic attributed to THEM (the restore's per-group tier/
        hedge evidence) pass a ledger dict — the global ``stats`` counters
        are shared by every concurrent caller (a classic materialize
        running beside a weight stream), so differencing them would
        misattribute the neighbor's traffic."""
        if ledger is not None:
            ledger[key] = ledger.get(key, 0) + n

    def advertise_group(self, key: str) -> None:
        """Scale-out plane (ISSUE 17): mark one COMPLETE shard group
        (content key) as re-servable from this cache. The restore path
        calls this after a group's last shard landed; the worker
        heartbeat ships it via :meth:`snapshot`, and the coordinator
        turns it into tree edges for joining replicas."""
        if key:
            self.groups.add(key)

    def snapshot(self) -> dict:
        """Cache-plane evidence for the worker heartbeat → timeline /
        /api/v1/metrics path: tier counters, hedge outcomes, per-peer
        EWMAs/bytes/histograms (ISSUE 13), plus the complete shard
        groups this cache re-serves + its serve address (ISSUE 17 —
        the coordinator's holders/edge-weight inputs)."""
        peers = {}
        for peer, entry in self._peer_stats.items():
            peers[peer] = {
                "lat_ewma_s": round(self._peer_lat.get(peer, 0.0), 6),
                "mean_s": round(entry["total_s"] / entry["exchanges"], 6)
                if entry["exchanges"] else 0.0,
                "exchanges": entry["exchanges"], "bytes": entry["bytes"],
                "errors": entry["errors"], "hist": list(entry["hist"])}
        return {**self.stats,
                "lat_ewma_global_s": round(self._peer_lat_ewma, 6),
                "hist_buckets_s": list(self.LAT_BUCKETS_S),
                "addr": self.self_address,
                "groups": sorted(self.groups),
                "peers": peers}

    # -- public API ---------------------------------------------------------

    async def _peer_get_verified(self, peer: str,
                                 digest: str) -> Optional[bytes]:
        """A peer result counts ONLY if its hash matches — hedged or not,
        an unverified chunk must never win the race."""
        import time
        t0 = time.monotonic()
        data = await self._peer_get(peer, digest)
        if data is not None and chunk_hash(data) == digest:
            self._note_exchange(peer, time.monotonic() - t0, len(data))
            return data
        if data is not None:           # answered, but corrupt — count it
            # in BOTH ledgers: the per-peer series and the worker-level
            # peer_errors counter must not contradict each other
            self.stats["peer_errors"] += 1
            self._peer_entry(peer)["errors"] += 1
        return None

    async def _hedged_peer_get(self, ordered: Sequence[str], digest: str,
                               ledger: Optional[dict] = None
                               ) -> tuple[Optional[bytes], str]:
        """Race the HRW-ordered peers for one chunk: peer *i+1* launches
        only after peer *i* has had ``hedge_delay_s`` to answer; the first
        verified result wins and every other in-flight try is cancelled
        (with its connection dropped — see ``_peer_get``). Returns
        ``(data, winning_peer)`` so the caller can attribute the bytes to
        the serving replica (the per-edge evidence — ISSUE 17)."""
        if not ordered:
            return None, ""
        if len(ordered) == 1:
            # nobody to hedge with — skip the task/wait machinery, which
            # costs real throughput on the per-chunk hot path
            return (await self._peer_get_verified(ordered[0], digest),
                    ordered[0])
        tasks: list[asyncio.Task] = []
        task_peer: dict[asyncio.Task, str] = {}
        winner: Optional[bytes] = None
        winner_peer = ""
        try:
            nxt = 0
            pending: set[asyncio.Task] = set()
            while winner is None and (pending or nxt < len(ordered)):
                if nxt < len(ordered) and (not pending
                                           or self.hedge_delay_s >= 0):
                    task = asyncio.create_task(
                        self._peer_get_verified(ordered[nxt], digest))
                    tasks.append(task)
                    task_peer[task] = ordered[nxt]
                    pending.add(task)
                    nxt += 1
                # the head start adapts to the history of the PEER we are
                # waiting on (best-ranked still pending — tasks is launch
                # = rank order), not a global average: a slow peer
                # elsewhere in the fleet must not delay hedging against
                # THIS peer, and a known-slow primary earns a
                # proportionally longer window before its hedge fires
                waiting_on = next(
                    (task_peer[t] for t in tasks if t in pending),
                    ordered[0])
                timeout = None if (nxt >= len(ordered)
                                   or self.hedge_delay_s < 0) \
                    else max(self.hedge_delay_s,
                             2.0 * self._lat_estimate(waiting_on))
                done, pending = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                if not done and nxt < len(ordered):
                    self.stats["hedged_reads"] += 1   # launching a hedge
                    self._tally(ledger, "hedged_reads")
                # deterministic preference: the EARLIEST-ranked completed
                # try wins a same-wakeup tie, so hedge_wins attribution is
                # stable and a completed loser's bytes count as waste
                for task in tasks:
                    if task not in done:
                        continue
                    try:
                        data = task.result()
                    except Exception:   # noqa: BLE001 — a lost racer only
                        data = None     # loses; the race itself survives
                    if data is None:
                        continue
                    if winner is None:
                        winner = data
                        winner_peer = task_peer[task]
                        if task is not tasks[0]:
                            self.stats["hedge_wins"] += 1
                            self._tally(ledger, "hedge_wins")
                    else:
                        # a hedge that completed after the race was
                        # decided moved real bytes for nothing — the
                        # cost side of the hedging ledger
                        self.stats["hedge_wasted_bytes"] += len(data)
                        self._tally(ledger, "hedge_wasted_bytes",
                                    len(data))
            return winner, winner_peer
        finally:
            for task in tasks:
                if not task.done():
                    task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    async def get(self, digest: str,
                  ledger: Optional[dict] = None,
                  prefer: Optional[Sequence[str]] = None) -> Optional[bytes]:
        """local → hedged HRW peers → source (populating local + primary).
        ``ledger`` receives THIS call's tier/hedge accounting (see
        :meth:`_tally`). ``prefer`` (ISSUE 17) is the distribution tree's
        parent preference list: those peers are raced FIRST, in order,
        with the HRW remainder behind them — so a dead parent falls
        through to surviving holders inside the same hedged read, and
        the source tier stays the last resort either way."""
        data = await self.store.get(digest)
        if data is not None:
            self.stats["local_hits"] += 1
            self.stats["bytes_local"] += len(data)
            self._tally(ledger, "local_hits")
            self._tally(ledger, "bytes_local", len(data))
            return data

        peers = [p for p in await self.peers() if p != self.self_address]
        ordered = hrw_order(digest, peers)[: max(self.replicas, 1) + 1]
        if prefer:
            tree = [p for p in prefer
                    if p in peers and p != self.self_address]
            ordered = tree + [p for p in ordered if p not in tree]
        data, served_by = await self._hedged_peer_get(ordered, digest,
                                                      ledger=ledger)
        if data is not None:
            self.stats["peer_hits"] += 1
            self.stats["bytes_peer"] += len(data)
            self._tally(ledger, "peer_hits")
            self._tally(ledger, "bytes_peer", len(data))
            if served_by:
                # per-EDGE attribution (ISSUE 17 satellite: the coldstart
                # record's one "peer" tier hid which replica served what)
                self._tally(ledger, f"bytes_peer:{served_by}", len(data))
            await self.store.put(data, digest)
            return data

        if self.source is not None:
            data = await self.source(digest)
            if data is not None:
                self.stats["source_fetches"] += 1
                self.stats["bytes_source"] += len(data)
                self._tally(ledger, "source_fetches")
                self._tally(ledger, "bytes_source", len(data))
                await self.store.put(data, digest)
                # seed the canonical holder so the next reader hits a peer
                ordered = hrw_order(digest, peers)
                if ordered:
                    self._spawn_bg(self._peer_put(ordered[0], digest, data))
                return data
        return None

    async def get_stream(self, digests: Sequence[str],
                         window: int = 8,
                         ledger: Optional[dict] = None,
                         prefer: Optional[Sequence[str]] = None
                         ) -> AsyncIterator[
                             tuple[str, Optional[bytes]]]:
        """Yield ``(digest, data)`` in the given (manifest) order through a
        read-ahead window — the streaming-restore feed: chunk *i+1* is in
        flight while the consumer deserializes chunk *i*. Duplicate digests
        are served again (second fetch is a local-store hit). ``ledger``
        attributes exactly this stream's tier/hedge traffic to the caller
        (the per-group restore evidence); ``prefer`` carries the tree
        parents for the group this stream restores (ISSUE 17)."""
        from .prefetch import Prefetcher

        async def fetch(digest: str) -> Optional[bytes]:
            return await self.get(digest, ledger=ledger, prefer=prefer)

        pf = Prefetcher(fetch, list(dict.fromkeys(digests)),
                        window=window)
        try:
            for digest in digests:
                yield digest, await pf.get(digest)
        finally:
            await pf.close()

    async def put(self, data: bytes, digest: str = "") -> str:
        digest = digest or chunk_hash(data)
        await self.store.put(data, digest)
        peers = [p for p in await self.peers() if p != self.self_address]
        ordered = hrw_order(digest, peers)[: self.replicas]
        if ordered:
            # replica fan-out in parallel: N sequential peer round-trips
            # serialized every snapshot upload (ISSUE 1 satellite)
            await asyncio.gather(*[self._peer_put(peer, digest, data)
                                   for peer in ordered])
        return digest

    # -- kv: namespace (ISSUE 16) -------------------------------------------
    # Shipped paged-KV blocks ride the SAME content-addressed transport
    # as weight chunks (HRW placement, hedged verified reads, replica
    # fan-out) — digests stay plain chunk hashes because peer reads
    # verify `chunk_hash(data) == digest`. The namespace is a ledger
    # split, not a wire change: these wrappers attribute the traffic.

    async def put_kv(self, payload: bytes) -> str:
        """Publish one kvwire payload; returns its content digest (the
        key an SSE ``kv_key`` event / drain hand-off carries)."""
        digest = await self.put(payload)
        self.stats["kv_puts"] += 1
        self.stats["kv_bytes_put"] += len(payload)
        return digest

    async def get_kv(self, digest: str) -> Optional[bytes]:
        """Fetch one shipped payload (local → hedged peers → source)."""
        data = await self.get(digest)
        if data is None:
            self.stats["kv_misses"] += 1
            return None
        self.stats["kv_gets"] += 1
        self.stats["kv_bytes_get"] += len(data)
        return data

    async def get_many(self, digests: Sequence[str],
                       max_parallel: int = 8) -> dict[str, Optional[bytes]]:
        """Parallel fetch with bounded concurrency (prefetch window —
        reference prefetcher.go:49)."""
        sem = asyncio.Semaphore(max_parallel)
        out: dict[str, Optional[bytes]] = {}

        async def one(d: str) -> None:
            async with sem:
                out[d] = await self.get(d)

        await asyncio.gather(*[one(d) for d in dict.fromkeys(digests)])
        return out
