"""Embedded distributed content cache.

Reference analogue: ``pkg/cache/`` (~18k LoC) — the peer-to-peer
content-addressed cache behind image pulls, volume reads, and checkpoint
artifacts: rendezvous/HRW client (client.go:187), raw-TCP server
(raw_transport.go), disk store with eviction (storage.go:71), prefetcher.

tpu9's design (protocol ideas, not a port): chunks are sha256-addressed blobs
(default 4 MiB). Every worker runs a ChunkServer over its DiskStore; clients
route by HRW over the live peer set from the worker registry, fall back to
any holder, then to the source-of-truth store (the gateway registry dir /
object storage). The TCP framing is shared with the state bus (msgpack
header + raw payload) so one wire stack serves both.
"""

from .store import DiskStore
from .server import ChunkServer
from .client import CacheClient, hrw_order

__all__ = ["DiskStore", "ChunkServer", "CacheClient", "hrw_order"]
