"""CacheFS: FUSE read-through views of chunk manifests.

Reference analogue: ``pkg/cache/cachefs.go:47`` — the reference mounts a
FUSE filesystem whose reads pull content from the embedded distributed
cache. tpu9's mount daemon is ``native/t9cachefs`` (speaks the kernel
FUSE protocol directly, no libfuse); this manager owns its lifecycle and
serves its chunk-fault socket: when the filesystem needs a chunk that is
not yet in the node's DiskStore, it sends ``CHUNK <digest>`` here and the
CacheClient pulls it (local → HRW peers → source) before the read
resumes.

This covers the readers the LD_PRELOAD shims cannot: static binaries,
mmap, direct syscalls — page faults stream exactly the chunks touched.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import time
from typing import Optional

from ..images.manifest import ImageManifest
from .client import CacheClient

log = logging.getLogger("tpu9.cache")

from ..utils import native_binary

_BIN = native_binary("t9cachefs")


class CacheFsMount:
    def __init__(self, mountpoint: str, proc: subprocess.Popen,
                 server: asyncio.AbstractServer, sock_path: str,
                 manifest_path: str):
        self.mountpoint = mountpoint
        self._proc = proc
        self._server = server
        self._sock_path = sock_path
        self._manifest_path = manifest_path
        self.stats = {"faults": 0, "fault_failures": 0}

    async def unmount(self) -> None:
        """Tear down this mount. Callers that went through CacheFsManager
        should prefer ``manager.unmount(mountpoint)`` so the manager's
        mount table stays the single source of truth."""
        subprocess.run(["umount", self.mountpoint], capture_output=True)
        try:
            self._proc.kill()
        except ProcessLookupError:
            pass
        self._server.close()
        try:
            await self._server.wait_closed()
        except Exception:          # noqa: BLE001
            pass
        for p in (self._sock_path, self._manifest_path):
            try:
                os.unlink(p)
            except OSError:
                pass


class CacheFsManager:
    def __init__(self, cache: CacheClient, work_dir: str):
        self.cache = cache
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        self._mounts: dict[str, CacheFsMount] = {}

    @staticmethod
    def supported() -> bool:
        return (os.path.exists("/dev/fuse") and os.path.exists(_BIN)
                and os.geteuid() == 0)

    async def mount(self, manifest: ImageManifest,
                    mountpoint: str) -> CacheFsMount:
        os.makedirs(mountpoint, exist_ok=True)
        import hashlib
        tag = manifest.image_id or manifest.manifest_hash[:12]
        if len(tag) > 32:
            # the fault socket must fit AF_UNIX's ~108-byte path budget
            # even under deep work dirs — long ids (volume manifests embed
            # workspace+name+fingerprint) get a stable digest tag instead
            tag = hashlib.sha256(tag.encode()).hexdigest()[:16]
        # the MOUNTPOINT disambiguates concurrent mounts of the same
        # manifest (two containers sharing a volume): a tag-only path
        # would make the second mount unlink the first's live fault socket
        tag += "-" + hashlib.sha256(mountpoint.encode()).hexdigest()[:8]
        manifest_path = os.path.join(self.work_dir, f"{tag}.manifest.json")
        with open(manifest_path, "w") as f:
            f.write(manifest.to_json())
        sock_path = os.path.join(self.work_dir, f"{tag}.fault.sock")
        try:
            os.unlink(sock_path)
        except OSError:
            pass

        mount: Optional[CacheFsMount] = None

        async def serve_fault(reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
            try:
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    parts = line.decode(errors="replace").split()
                    if len(parts) != 2 or parts[0] != "CHUNK":
                        writer.write(b"ERR\n")
                        await writer.drain()
                        continue
                    # get() stores the chunk in the DiskStore on the way
                    # through — exactly where t9cachefs rereads it
                    data = await self.cache.get(parts[1])
                    if mount is not None:
                        mount.stats["faults"] += 1
                        if data is None:
                            mount.stats["fault_failures"] += 1
                    writer.write(b"OK\n" if data is not None else b"ERR\n")
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:      # noqa: BLE001
                    pass

        server = await asyncio.start_unix_server(serve_fault,
                                                 path=sock_path)
        os.chmod(sock_path, 0o666)

        proc = subprocess.Popen(
            [_BIN, "--manifest", manifest_path,
             "--store", self.cache.store.root,
             "--mount", mountpoint, "--sock", sock_path, "--foreground"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        # wait for the mount to go live: a mounted FUSE root is a DIFFERENT
        # device than its parent directory (statfs fields are too generic
        # to distinguish reliably)
        def _fail_cleanup() -> None:
            # leave NOTHING behind: a live mount at the bundle path would
            # wedge every later pull of this image (rmtree can't remove a
            # read-only mount, rename next to it gets EBUSY)
            subprocess.run(["umount", "-l", mountpoint],
                           capture_output=True)
            try:
                proc.kill()
            except ProcessLookupError:
                pass
            server.close()
            for p in (sock_path, manifest_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass

        parent_dev = os.stat(os.path.dirname(mountpoint.rstrip("/"))
                             or "/").st_dev
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                err = (proc.stderr.read() or b"").decode(errors="replace")
                _fail_cleanup()
                raise RuntimeError(f"t9cachefs died: {err.strip()}")
            try:
                if os.stat(mountpoint).st_dev != parent_dev:
                    break
            except OSError:
                pass
            await asyncio.sleep(0.02)
        else:
            _fail_cleanup()
            raise RuntimeError("t9cachefs mount did not come up")

        mount = CacheFsMount(mountpoint, proc, server, sock_path,
                             manifest_path)
        self._mounts[mountpoint] = mount
        log.info("cachefs: %d files mounted at %s", len(manifest.files),
                 mountpoint)
        return mount

    async def unmount(self, mountpoint: str) -> None:
        """Drop the registry entry and tear the mount down — keeps the
        mount table owned in exactly one place."""
        mount = self._mounts.pop(mountpoint, None)
        if mount is not None:
            await mount.unmount()

    async def close(self) -> None:
        for mount in list(self._mounts.values()):
            await mount.unmount()
        self._mounts.clear()
