"""Sliding-window prefetcher for sequential chunk streams.

Reference analogue: ``pkg/cache/prefetcher.go:49`` — read-ahead so a
consumer walking chunks in order (manifest materialization: disk/sandbox
snapshot restores, image pulls) overlaps fetch latency with consumption
instead of paying one round-trip per chunk serially.

Works over ANY async fetch function — the cache client, the gateway chunk
HTTP hooks workers use, or a GCS source — because the restore paths are
hook-injected and don't all go through ``CacheClient``.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional, Sequence

Fetch = Callable[[str], Awaitable[Optional[bytes]]]


class Prefetcher:
    """Feed it the ordered digest list once; call ``get`` in (roughly) that
    order. A window of background fetches runs ahead of the consumer;
    out-of-order gets still work (they just fetch on demand)."""

    def __init__(self, fetch: Fetch, digests: Sequence[str],
                 window: int = 8):
        self.fetch = fetch
        self.order = list(digests)
        self.window = max(window, 1)
        self._tasks: dict[str, asyncio.Task] = {}
        self._done: set[str] = set()   # consumed — never re-scheduled
        self._next = 0          # first order-index not yet scheduled
        self._closed = False

    def _schedule_ahead(self) -> None:
        while (not self._closed and self._next < len(self.order)
               and len(self._tasks) < self.window):
            digest = self.order[self._next]
            self._next += 1
            if digest not in self._tasks and digest not in self._done:
                self._tasks[digest] = asyncio.ensure_future(
                    self.fetch(digest))

    async def get(self, digest: str) -> Optional[bytes]:
        self._schedule_ahead()
        self._done.add(digest)   # out-of-order gets must not refetch later
        task = self._tasks.pop(digest, None)
        if task is None:
            data = await self.fetch(digest)
        else:
            try:
                data = await task
            except asyncio.CancelledError:
                # consumer aborted mid-await: the popped task is no longer
                # in _tasks, so close() can't reach it — cancel it here or
                # the fetch (and its connection) outlives the restore
                task.cancel()
                raise
        self._schedule_ahead()
        return data

    async def close(self) -> None:
        """Cancel every in-flight read-ahead and await it out: after close
        returns there are no pending tasks, and a racing ``get`` can never
        schedule new ones (the restore path closes mid-stream on failure)."""
        self._closed = True
        for task in self._tasks.values():
            task.cancel()
        await asyncio.gather(*self._tasks.values(), return_exceptions=True)
        self._tasks.clear()


def threadsafe_get(prefetcher: Prefetcher, loop: asyncio.AbstractEventLoop):
    """Adapter for ``materialize`` running in a worker thread: a sync
    ``get_chunk`` that drives the prefetcher on the event loop."""
    def get_chunk(digest: str) -> Optional[bytes]:
        return asyncio.run_coroutine_threadsafe(
            prefetcher.get(digest), loop).result()
    return get_chunk


def threadsafe_put(chunk_put, loop: asyncio.AbstractEventLoop):
    """Write-side twin of ``threadsafe_get``: a sync ``put_chunk`` for
    ``snapshot_dir`` running in a worker thread, driving an async chunk
    sink on the event loop (shared by disk/sandbox/criu snapshots)."""
    def put_chunk(data: bytes, digest: str) -> None:
        asyncio.run_coroutine_threadsafe(
            chunk_put(data, digest), loop).result()
    return put_chunk
