"""Mixtral-family (sparse MoE decoder) configs.

Architecture constants follow the public Mixtral-8x7B card (Llama-shaped
attention + 8-expert top-2 sparse FFN). No reference analogue — the
reference delegates models to user containers (SURVEY.md §2.10); tpu9
ships the family so `ep`-sharded serving works out of the box.
"""

from __future__ import annotations

from .transformer import DecoderConfig


def mixtral_config(**kw) -> DecoderConfig:
    base = dict(act="silu", norm_offset=0.0, rope_theta=1e6,
                norm_eps=1e-5, tie_embeddings=False,
                n_experts=8, moe_top_k=2)
    base.update(kw)
    return DecoderConfig(**base)


MIXTRAL_PRESETS: dict[str, DecoderConfig] = {
    # test-scale: 4 experts, exercised by unit tests / CPU dry-runs
    "mixtral-tiny": mixtral_config(vocab_size=512, dim=128, n_layers=2,
                                   n_heads=4, n_kv_heads=2, head_dim=32,
                                   hidden_dim=256, max_seq_len=512,
                                   n_experts=4),
    # Mixtral-8x7B: 47B total / ~13B active per token
    "mixtral-8x7b": mixtral_config(vocab_size=32000, dim=4096, n_layers=32,
                                   n_heads=32, n_kv_heads=8, head_dim=128,
                                   hidden_dim=14336, max_seq_len=32768),
}
