"""JAX model zoo for tpu9 runner workloads.

These are the in-container workloads of the baseline configs (BASELINE.md):
text classifier (CPU), Llama 3 (v5e serving), CLIP ViT (fan-out embedding),
Gemma + LoRA (multi-host FSDP fine-tune). All models are functional pytrees —
params flow through ``jax.jit``/``pjit`` with shardings from tpu9.parallel.
"""

from .transformer import DecoderConfig, init_decoder, decoder_forward, init_kv_cache
from .llama import LLAMA_PRESETS, llama_config
from .gemma import GEMMA_PRESETS, gemma_config
from .clip_vit import ClipVisionConfig, init_clip_vision, clip_vision_forward, CLIP_VIT_L14
from .classifier import TextClassifierConfig, init_classifier, classifier_forward
from . import lora, moe
from .mixtral import MIXTRAL_PRESETS, mixtral_config

__all__ = [
    "DecoderConfig", "init_decoder", "decoder_forward", "init_kv_cache",
    "LLAMA_PRESETS", "llama_config", "GEMMA_PRESETS", "gemma_config",
    "MIXTRAL_PRESETS", "mixtral_config", "moe",
    "ClipVisionConfig", "init_clip_vision", "clip_vision_forward", "CLIP_VIT_L14",
    "TextClassifierConfig", "init_classifier", "classifier_forward", "lora",
]
