"""Sparse mixture-of-experts FFN with expert parallelism.

Reference has no in-framework MoE (SURVEY.md §2.10 — parallelism is
delegated to user containers); this module is part of tpu9's TPU-first
compute layer alongside TP/FSDP/ring attention.

TPU-first design (GShard/Switch dispatch, not scatter/gather): routing
builds a dense one-hot dispatch tensor ``[tokens, experts, capacity]`` and
all data movement is einsums — which XLA lowers to all-to-alls when the
expert dimension is sharded over the ``ep`` mesh axis, keeping every
FLOP on the MXU and every transfer on ICI. No dynamic shapes, no host
control flow: over-capacity tokens are dropped (their residual stream
passes through untouched), exactly the standard capacity-factor contract.

Params layout: every expert tensor has a leading ``n_experts`` dim sharded
``P("ep")`` — one ``ep`` shard holds ``n_experts / ep`` full experts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Any


@dataclass(frozen=True)
class MoeConfig:
    dim: int = 512
    hidden_dim: int = 1024
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    act: str = "silu"
    dtype: Any = jnp.bfloat16


def init_moe_layer(rng: jax.Array, cfg: MoeConfig) -> Params:
    r1, r2, r3, r4 = jax.random.split(rng, 4)
    dt = cfg.dtype
    e, d, h = cfg.n_experts, cfg.dim, cfg.hidden_dim

    def dense(r, shape, fan):
        scale = (2.0 / sum(fan)) ** 0.5
        return (jax.random.normal(r, shape, jnp.float32) * scale).astype(dt)

    return {
        "router": dense(r1, (d, e), (d, e)).astype(jnp.float32),
        "w_gate": dense(r2, (e, d, h), (d, h)),
        "w_up": dense(r3, (e, d, h), (d, h)),
        "w_down": dense(r4, (e, h, d), (h, d)),
    }


def moe_param_specs(params: Params, axis: str = "ep") -> Params:
    """Sharding: router replicated, expert stacks sharded over ``axis``
    (the expert-parallel axis by default; decoder_param_specs passes tp
    for mixtral layers on plain serving meshes). Per-expert int8 entries
    (``{q: [E,in,out], scale: [E,1,out]}`` — tpu9.ops.quant) shard both
    planes along the expert axis, mirroring sharding._quant_aware for
    the dense 2-D weights."""

    def stack(leaf):
        from ..ops.quant import is_quantized_entry
        spec = P(axis, None, None)
        if is_quantized_entry(leaf):
            return {"q": spec, "scale": spec}
        return spec

    return {
        "router": P(),
        "w_gate": stack(params["w_gate"]),
        "w_up": stack(params["w_up"]),
        "w_down": stack(params["w_down"]),
    }


def _capacity(n_tokens: int, cfg: MoeConfig) -> int:
    cap = int(cfg.top_k * n_tokens * cfg.capacity_factor / cfg.n_experts)
    # capacity must be static, positive, and lane-friendly
    return max(8, -(-cap // 8) * 8)


def moe_ffn(params: Params, x: jnp.ndarray, cfg: MoeConfig,
            ep_sharded: bool = True):
    """x: [B, T, dim] → ([B, T, dim], aux) where aux carries the
    load-balancing loss (Switch §2.2: E * Σ_e f_e·p_e) and router stats.

    Dropped tokens (over expert capacity) contribute zero here — callers
    add the residual stream, so they pass through unchanged.
    """
    b, t, d = x.shape
    n = b * t
    e, k = cfg.n_experts, cfg.top_k
    c = _capacity(n, cfg)
    xf = x.reshape(n, d)

    # -- routing (f32 for numerics) ------------------------------------------
    logits = xf.astype(jnp.float32) @ params["router"]          # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)               # [N, k]
    # renormalize the chosen gates so outputs are a convex combination
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # one-hot expert assignment per (token, slot): [N, k, E]
    assign = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)

    # position of each (token, slot) within its expert's buffer: running
    # count of earlier claims on the same expert (token-major, slot-minor
    # priority — earlier tokens win capacity, the GShard convention)
    flat = assign.reshape(n * k, e)
    pos = jnp.cumsum(flat, axis=0) - flat                        # [N*k, E]
    pos = (pos * flat).sum(-1).reshape(n, k).astype(jnp.int32)   # [N, k]
    in_cap = (pos < c).astype(jnp.float32)

    # dispatch [N, E, C]: 1 where token n goes to expert e at slot c
    slot_oh = jax.nn.one_hot(pos, c, dtype=jnp.float32)          # [N, k, C]
    dispatch = jnp.einsum("nke,nkc->nec", assign, slot_oh * in_cap[..., None])
    combine = jnp.einsum("nke,nkc,nk->nec", assign,
                         slot_oh * in_cap[..., None], gate_vals)

    # -- expert compute (leading E dim sharded over ep) ----------------------
    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(cfg.dtype),
                    xf.astype(cfg.dtype))                        # [E, C, d]
    if ep_sharded:
        xe = jax.lax.with_sharding_constraint(xe, P("ep", None, None))
    # maybe_einsum: expert stacks may be per-expert int8 entries
    # (tpu9.ops.quant.quantize_weight_stacked) — the int8 operand stays
    # int8 in HBM, scales [E, 1, out] apply on the einsum output
    from ..ops.quant import maybe_einsum
    h = maybe_einsum("ecd,edh->ech", xe, params["w_gate"])
    if cfg.act == "silu":
        h = jax.nn.silu(h)
    else:
        h = jax.nn.gelu(h, approximate=True)
    h = h * maybe_einsum("ecd,edh->ech", xe, params["w_up"])
    ye = maybe_einsum("ech,ehd->ecd", h, params["w_down"])       # [E, C, d]
    if ep_sharded:
        ye = jax.lax.with_sharding_constraint(ye, P("ep", None, None))

    out = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), ye)

    # -- aux: load-balance loss + stats --------------------------------------
    # fraction of tokens whose TOP-1 lands on e, times mean router prob
    top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    frac_tokens = top1.mean(0)
    mean_prob = probs.mean(0)
    balance_loss = e * jnp.sum(frac_tokens * mean_prob)
    dropped = 1.0 - in_cap.mean()
    aux = {"balance_loss": balance_loss, "dropped_frac": dropped,
           "expert_load": frac_tokens}
    return out.reshape(b, t, d).astype(x.dtype), aux
