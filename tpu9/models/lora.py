"""LoRA adapters over the decoder param tree (baseline config #5: Gemma-7B
LoRA fine-tune).

Functional design: adapters live in a *separate* pytree shaped like
``{"layers": [{"wq": {"a": ..., "b": ...}, ...}]}`` — pure arrays, so the tree
is directly differentiable/optimizable. The ``alpha/rank`` scale is a static
float passed alongside. ``merge`` folds adapters into the base weights for
serving; training takes grads wrt the adapter tree only (the base stays
frozen — the property that makes multi-host FSDP fine-tunes cheap in
optimizer memory)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora(rng: jax.Array, params: Params, rank: int = 8,
              targets=DEFAULT_TARGETS) -> Params:
    adapters: Params = {"layers": []}
    for layer in params["layers"]:
        entry = {}
        for name in targets:
            if name not in layer:
                continue
            w = layer[name]
            rng, ra = jax.random.split(rng)
            entry[name] = {
                "a": (jax.random.normal(ra, (w.shape[0], rank),
                                        dtype=jnp.float32) / rank),
                "b": jnp.zeros((rank, w.shape[1]), dtype=jnp.float32),
            }
        adapters["layers"].append(entry)
    return adapters


def lora_scale(rank: int, alpha: float = 16.0) -> float:
    return alpha / rank


def merge(params: Params, adapters: Params,
          scale: Optional[float] = None) -> Params:
    """Return a new param tree with LoRA deltas folded into the base
    weights. ``scale=None`` derives alpha/rank from each adapter's actual
    rank (a hardcoded default would silently double/halve the deltas the
    training run optimized whenever rank != alpha/default)."""
    merged_layers = []
    for layer, ad_layer in zip(params["layers"], adapters["layers"]):
        new_layer = dict(layer)
        for name, ad in ad_layer.items():
            s = scale if scale is not None else lora_scale(ad["a"].shape[1])
            delta = (ad["a"] @ ad["b"]) * s
            new_layer[name] = (layer[name].astype(jnp.float32)
                               + delta).astype(layer[name].dtype)
        merged_layers.append(new_layer)
    out = dict(params)
    out["layers"] = merged_layers
    return out


def trainable_count(adapters: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(adapters))
