"""Gemma family configs (baseline config #5: Gemma-7B LoRA FSDP fine-tune on
v5p-64). Gemma differences from Llama handled by DecoderConfig switches:
GELU MLP, (1+w) RMSNorm, sqrt(dim) embedding scale, tied embeddings,
head_dim 256."""

from __future__ import annotations

from .transformer import DecoderConfig


def gemma_config(**kw) -> DecoderConfig:
    base = dict(act="gelu", norm_offset=1.0, embed_scale=True,
                tie_embeddings=True, rope_theta=10000.0, norm_eps=1e-6)
    base.update(kw)
    return DecoderConfig(**base)


GEMMA_PRESETS: dict[str, DecoderConfig] = {
    "gemma-tiny": gemma_config(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                               n_kv_heads=4, head_dim=32, hidden_dim=512,
                               max_seq_len=512),
    "gemma-2b": gemma_config(vocab_size=256128, dim=2048, n_layers=18,
                             n_heads=8, n_kv_heads=1, head_dim=256,
                             hidden_dim=16384, max_seq_len=8192),
    "gemma-7b": gemma_config(vocab_size=256128, dim=3072, n_layers=28,
                             n_heads=16, n_kv_heads=16, head_dim=256,
                             hidden_dim=24576, max_seq_len=8192),
}
