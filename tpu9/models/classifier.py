"""Small bidirectional text classifier (baseline config #1: distilbert-style
sentiment endpoint on CPU-only containers). Six-layer encoder, mean-pool,
linear head — small enough that CPU containers serve it at interactive
latency."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclass(frozen=True)
class TextClassifierConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    hidden_dim: int = 3072
    max_len: int = 512
    n_classes: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.float32


TEXTCLS_TINY = TextClassifierConfig(vocab_size=1024, dim=64, n_layers=2,
                                    n_heads=4, hidden_dim=128, max_len=128)


def _dense(rng, i, o, dtype):
    return (jax.random.normal(rng, (i, o), dtype=jnp.float32)
            * (2.0 / (i + o)) ** 0.5).astype(dtype)


def init_classifier(rng: jax.Array, cfg: TextClassifierConfig) -> Params:
    rngs = jax.random.split(rng, cfg.n_layers * 4 + 4)
    it = iter(rngs)
    dt = cfg.dtype
    params: Params = {
        "embed": (jax.random.normal(next(it), (cfg.vocab_size, cfg.dim),
                                    dtype=jnp.float32) * 0.02).astype(dt),
        "pos_embed": (jax.random.normal(next(it), (cfg.max_len, cfg.dim),
                                        dtype=jnp.float32) * 0.02).astype(dt),
        "head": _dense(next(it), cfg.dim, cfg.n_classes, dt),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "wqkv": _dense(next(it), cfg.dim, 3 * cfg.dim, dt),
            "wo": _dense(next(it), cfg.dim, cfg.dim, dt),
            "w1": _dense(next(it), cfg.dim, cfg.hidden_dim, dt),
            "w2": _dense(next(it), cfg.hidden_dim, cfg.dim, dt),
            "ln1_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln1_bias": jnp.zeros((cfg.dim,), jnp.float32),
            "ln2_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln2_bias": jnp.zeros((cfg.dim,), jnp.float32),
        })
    return params


def _ln(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * scale + bias).astype(x.dtype)


def classifier_forward(params: Params, tokens: jnp.ndarray,
                       mask: jnp.ndarray, cfg: TextClassifierConfig) -> jnp.ndarray:
    """tokens [B, T] int32, mask [B, T] {0,1} → logits [B, n_classes]."""
    b, t = tokens.shape
    head_dim = cfg.dim // cfg.n_heads
    x = params["embed"][tokens] + params["pos_embed"][None, :t]
    bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e30)  # [B,1,1,T]

    for layer in params["layers"]:
        qkv = (x @ layer["wqkv"]).reshape(b, t, 3, cfg.n_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum("bthd,bshd->bhts",
                            q.astype(jnp.float32) * head_dim ** -0.5,
                            k.astype(jnp.float32)) + bias
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhts,bshd->bthd", probs,
                          v.astype(jnp.float32)).astype(x.dtype)
        x = _ln(x + attn.reshape(b, t, cfg.dim) @ layer["wo"],
                layer["ln1_scale"], layer["ln1_bias"], cfg.norm_eps)
        h = jax.nn.gelu(x @ layer["w1"], approximate=True) @ layer["w2"]
        x = _ln(x + h, layer["ln2_scale"], layer["ln2_bias"], cfg.norm_eps)

    denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
    pooled = (x * mask[..., None]).sum(axis=1) / denom
    return (pooled @ params["head"]).astype(jnp.float32)
