"""Decoder-only transformer core shared by the Llama and Gemma families.

Functional style: ``init_decoder`` builds a param pytree (nested dicts with
stable path names the sharding rules in ``tpu9.parallel.sharding`` pattern-
match), ``decoder_forward`` runs prefill/train/decode from the same code path
with static shapes (XLA traces one graph per (batch, seq) bucket).

Weight layout is MXU-friendly: all projections stored as [in, out] so the
forward pass is plain ``x @ w`` row-major matmuls in bf16.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import attention, decode_attention
from ..ops.norms import rms_norm
from ..ops.quant import maybe_matmul, quantize_kv
from ..ops.rotary import apply_rope, rope_table

Params = dict[str, Any]


@dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    hidden_dim: int = 14336
    norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    max_seq_len: int = 8192
    # family switches
    act: str = "silu"              # silu (llama) | gelu (gemma)
    norm_offset: float = 0.0       # 1.0 for gemma's (1+w) RMSNorm
    embed_scale: bool = False      # gemma scales embeddings by sqrt(dim)
    logit_softcap: float = 0.0     # gemma-2 style; 0 = off
    tie_embeddings: bool = False   # output head = embed^T
    # sparse-MoE FFN (mixtral family): n_experts 0 = dense
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


def _dense_init(rng, in_dim: int, out_dim: int, dtype) -> jnp.ndarray:
    scale = (2.0 / (in_dim + out_dim)) ** 0.5
    return (jax.random.normal(rng, (in_dim, out_dim), dtype=jnp.float32)
            * scale).astype(dtype)


def init_decoder(rng: jax.Array, cfg: DecoderConfig) -> Params:
    n_rngs = cfg.n_layers * 7 + 3
    rngs = jax.random.split(rng, n_rngs)
    it = iter(range(n_rngs))
    dt = cfg.dtype

    def nxt():
        return rngs[next(it)]

    params: Params = {
        "embed": (jax.random.normal(nxt(), (cfg.vocab_size, cfg.dim),
                                    dtype=jnp.float32) * 0.02).astype(dt),
        "final_norm": jnp.ones((cfg.dim,), dtype=jnp.float32) - cfg.norm_offset,
        "layers": [],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(nxt(), cfg.dim, cfg.vocab_size, dt)
    else:
        nxt()

    q_dim = cfg.n_heads * cfg.head_dim
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    for li in range(cfg.n_layers):
        layer = {
            "attn_norm": jnp.ones((cfg.dim,), dtype=jnp.float32) - cfg.norm_offset,
            "mlp_norm": jnp.ones((cfg.dim,), dtype=jnp.float32) - cfg.norm_offset,
            "wq": _dense_init(nxt(), cfg.dim, q_dim, dt),
            "wk": _dense_init(nxt(), cfg.dim, kv_dim, dt),
            "wv": _dense_init(nxt(), cfg.dim, kv_dim, dt),
            "wo": _dense_init(nxt(), q_dim, cfg.dim, dt),
        }
        if cfg.n_experts:
            from .moe import MoeConfig, init_moe_layer
            layer["moe"] = init_moe_layer(
                jax.random.fold_in(nxt(), li), _moe_cfg(cfg))
            nxt(), nxt()   # keep the rng schedule aligned with dense
        else:
            layer["w_gate"] = _dense_init(nxt(), cfg.dim, cfg.hidden_dim, dt)
            layer["w_up"] = _dense_init(nxt(), cfg.dim, cfg.hidden_dim, dt)
            layer["w_down"] = _dense_init(nxt(), cfg.hidden_dim, cfg.dim, dt)
        params["layers"].append(layer)
    return params


def _moe_cfg(cfg: DecoderConfig):
    from .moe import MoeConfig
    return MoeConfig(dim=cfg.dim, hidden_dim=cfg.hidden_dim,
                     n_experts=cfg.n_experts, top_k=cfg.moe_top_k,
                     capacity_factor=cfg.moe_capacity_factor,
                     act=cfg.act, dtype=cfg.dtype)


def init_kv_cache(cfg: DecoderConfig, batch: int, max_len: int = 0,
                  dtype=None) -> Params:
    """Contiguous per-sequence KV cache: k/v [L, B, S, KH, D]."""
    s = max_len or cfg.max_seq_len
    dt = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, s, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype=dt), "v": jnp.zeros(shape, dtype=dt)}


def _act(x: jnp.ndarray, kind: str) -> jnp.ndarray:
    if kind == "silu":
        return jax.nn.silu(x)
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(kind)


def _attn_block(layer: Params, x: jnp.ndarray, cfg: DecoderConfig,
                positions: jnp.ndarray, sin, cos,
                kv_cache: Optional[Params], layer_idx: int,
                cache_len: Optional[jnp.ndarray], decode: bool):
    b, t, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps, cfg.norm_offset)
    q = maybe_matmul(h, layer["wq"]).reshape(b, t, cfg.n_heads, cfg.head_dim)
    k = maybe_matmul(h, layer["wk"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    v = maybe_matmul(h, layer["wv"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, sin, cos)
    k = apply_rope(k, positions, sin, cos)

    new_cache = None
    if kv_cache is None:
        out = attention(q, k, v, causal=True)
    elif decode and "table" in kv_cache:
        # paged decode: scatter this token's k/v into the slot's physical
        # pool block, then block-table paged attention over the prefix.
        # Pool layout [N_BLOCKS, BS, KH, D] is shared by all sequences —
        # prefix blocks can be referenced by many tables (prefix reuse).
        # An int8 pool ("k_scale" present) quantizes the write per
        # (token, head) vector and the attention dequantizes in-kernel.
        from ..ops.attention import paged_attention_dispatch
        table = kv_cache["table"]                      # [B, MB]
        bs = kv_cache["k"].shape[2]                    # [L,N,BS,KH,D]
        pos = positions[:, 0]                          # [B]
        rows = jnp.arange(b)
        bi = table[rows, pos // bs]
        oi = pos % bs
        if "k_scale" in kv_cache:
            qk, sk = quantize_kv(k[:, 0])              # [B,KH,D], [B,KH]
            qv, sv = quantize_kv(v[:, 0])
            k_pool = kv_cache["k"][layer_idx].at[bi, oi].set(qk)
            v_pool = kv_cache["v"][layer_idx].at[bi, oi].set(qv)
            k_sc = kv_cache["k_scale"][layer_idx].at[bi, oi].set(sk)
            v_sc = kv_cache["v_scale"][layer_idx].at[bi, oi].set(sv)
            out = paged_attention_dispatch(q, k_pool, v_pool, table,
                                           cache_len, k_sc, v_sc)
            new_cache = (k_pool, v_pool, k_sc, v_sc)
        else:
            k_pool = kv_cache["k"][layer_idx].at[bi, oi].set(k[:, 0])
            v_pool = kv_cache["v"][layer_idx].at[bi, oi].set(v[:, 0])
            out = paged_attention_dispatch(q, k_pool, v_pool, table,
                                           cache_len)
            new_cache = (k_pool, v_pool)
    elif "table" in kv_cache:
        # paged multi-token VERIFY (speculative decoding): scatter all T
        # window tokens' k/v into the slots' physical pool blocks in one
        # shot, then attend each query over its own absolute-position
        # prefix. Rejected draft positions simply hold garbage KV after
        # the window — attention masks by position, and the next window's
        # writes overwrite them (paged scratch re-splice semantics).
        from ..ops.attention import paged_verify_attention
        table = kv_cache["table"]                      # [B, MB]
        bs = kv_cache["k"].shape[2]                    # [L,N,BS,KH,D]
        bi = jnp.take_along_axis(table, positions // bs, axis=1)  # [B,T]
        oi = positions % bs
        if "k_scale" in kv_cache:
            qk, sk = quantize_kv(k)                    # [B,T,KH,D],[B,T,KH]
            qv, sv = quantize_kv(v)
            k_pool = kv_cache["k"][layer_idx].at[bi, oi].set(qk)
            v_pool = kv_cache["v"][layer_idx].at[bi, oi].set(qv)
            k_sc = kv_cache["k_scale"][layer_idx].at[bi, oi].set(sk)
            v_sc = kv_cache["v_scale"][layer_idx].at[bi, oi].set(sv)
            out = paged_verify_attention(q, k_pool, v_pool, table,
                                         positions, k_sc, v_sc)
            new_cache = (k_pool, v_pool, k_sc, v_sc)
        else:
            k_pool = kv_cache["k"][layer_idx].at[bi, oi].set(k)
            v_pool = kv_cache["v"][layer_idx].at[bi, oi].set(v)
            out = paged_verify_attention(q, k_pool, v_pool, table,
                                         positions)
            new_cache = (k_pool, v_pool)
    elif decode:
        # scatter this token's k/v at positions, then attend over the prefix
        k_cache = jax.lax.dynamic_update_slice(
            kv_cache["k"][layer_idx], k,
            (0, positions[0, 0], 0, 0)) if b == 1 else _scatter_kv(
                kv_cache["k"][layer_idx], k, positions)
        v_cache = jax.lax.dynamic_update_slice(
            kv_cache["v"][layer_idx], v,
            (0, positions[0, 0], 0, 0)) if b == 1 else _scatter_kv(
                kv_cache["v"][layer_idx], v, positions)
        out = decode_attention(q, k_cache, v_cache, cache_len)
        new_cache = (k_cache, v_cache)
    elif cache_len is not None:
        # CHUNKED prefill: write this chunk at its PER-ROW offset, then
        # attend over prefix + chunk with the absolute-position mask —
        # graph shapes are (C, S) no matter how long the prompt is. The
        # engine admits chunks at batch 1, but the signature accepts
        # [B, C] positions: applying row 0's offset to every row would
        # write other rows' chunks at the wrong cache slots (and their
        # queries would then mask out their own chunk) — silently wrong
        # logits, so scatter per row.
        from ..ops.attention import chunk_prefill_attention
        if b == 1:
            off = positions[0, 0]
            k_cache = jax.lax.dynamic_update_slice(
                kv_cache["k"][layer_idx], k, (0, off, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                kv_cache["v"][layer_idx], v, (0, off, 0, 0))
        else:
            def write_chunk(c, item, off0):
                return jax.lax.dynamic_update_slice(c, item, (off0, 0, 0))

            k_cache = jax.vmap(write_chunk)(
                kv_cache["k"][layer_idx], k, positions[:, 0])
            v_cache = jax.vmap(write_chunk)(
                kv_cache["v"][layer_idx], v, positions[:, 0])
        out = chunk_prefill_attention(q, k_cache, v_cache, positions)
        new_cache = (k_cache, v_cache)
    else:
        # prefill: write [0, t) then causal-attend within the prefix
        k_cache = jax.lax.dynamic_update_slice(
            kv_cache["k"][layer_idx], k, (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            kv_cache["v"][layer_idx], v, (0, 0, 0, 0))
        out = attention(q, k, v, causal=True)
        new_cache = (k_cache, v_cache)

    out = out.reshape(b, t, cfg.n_heads * cfg.head_dim)
    return x + maybe_matmul(out, layer["wo"]), new_cache


def _scatter_kv(cache: jnp.ndarray, kv: jnp.ndarray,
                positions: jnp.ndarray) -> jnp.ndarray:
    """Per-sequence scatter of one token: cache [B,S,KH,D], kv [B,1,KH,D],
    positions [B,1]."""
    b = cache.shape[0]
    idx = positions[:, 0]

    def write_one(c, item, i):
        return jax.lax.dynamic_update_slice(c, item, (i, 0, 0))

    return jax.vmap(write_one)(cache, kv, idx)


def _mlp_block(layer: Params, x: jnp.ndarray, cfg: DecoderConfig):
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps, cfg.norm_offset)
    if cfg.n_experts:
        from .moe import moe_ffn
        y, aux = moe_ffn(layer["moe"], h, _moe_cfg(cfg), ep_sharded=False)
        return x + y, aux
    gated = _act(maybe_matmul(h, layer["w_gate"]), cfg.act) * maybe_matmul(h, layer["w_up"])
    return x + maybe_matmul(gated, layer["w_down"]), None


def decoder_forward(params: Params, tokens: jnp.ndarray, cfg: DecoderConfig,
                    positions: Optional[jnp.ndarray] = None,
                    kv_cache: Optional[Params] = None,
                    cache_len: Optional[jnp.ndarray] = None,
                    decode: bool = False,
                    return_hidden: bool = False,
                    return_moe_aux: bool = False):
    """Run the decoder.

    - train/eval: ``decoder_forward(params, tokens, cfg)`` → logits [B,T,V]
    - prefill:   pass ``kv_cache`` (positions default to arange) → (logits, cache)
    - decode:    ``decode=True`` with tokens [B,1], positions [B,1], cache_len [B]
                 → (logits [B,1,V], cache)
    """
    b, t = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))

    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, dtype=cfg.dtype)

    # the rope table must cover every cache slot: positions past the table
    # are CLAMPED by JAX's gather, rotating distinct positions identically
    # (silent long-context degradation, no error) — catch the static-shape
    # mismatch at trace time instead
    rope_len = cfg.max_seq_len
    if kv_cache is not None and "table" not in kv_cache:
        cache_s = kv_cache["k"].shape[2]
        if cache_s > rope_len:
            raise ValueError(
                f"kv cache length {cache_s} exceeds rope table "
                f"{rope_len} — positions past it would alias")
    sin, cos = rope_table(rope_len, cfg.head_dim, cfg.rope_theta)

    updates: list = []        # per-layer (k, v[, k_scale, v_scale]) tuples
    moe_balance = jnp.zeros((), jnp.float32)
    for i, layer in enumerate(params["layers"]):
        x, updated = _attn_block(layer, x, cfg, positions, sin, cos,
                                 kv_cache, i, cache_len, decode)
        if updated is not None:
            updates.append(updated)
        x, aux = _mlp_block(layer, x, cfg)
        if aux is not None:
            moe_balance = moe_balance + aux["balance_loss"]

    x = rms_norm(x, params["final_norm"], cfg.norm_eps, cfg.norm_offset)
    if return_hidden:
        logits = None
    else:
        if cfg.tie_embeddings:
            logits = (x @ params["embed"].T.astype(cfg.dtype)).astype(jnp.float32)
        else:
            logits = maybe_matmul(x, params["lm_head"]).astype(jnp.float32)
        if cfg.logit_softcap > 0:
            logits = cfg.logit_softcap * jnp.tanh(logits / cfg.logit_softcap)

    out = x if return_hidden else logits

    def _pack_cache():
        cache = {"k": jnp.stack([u[0] for u in updates]),
                 "v": jnp.stack([u[1] for u in updates])}
        if updates and len(updates[0]) == 4:     # int8 pool: scales ride
            cache["k_scale"] = jnp.stack([u[2] for u in updates])
            cache["v_scale"] = jnp.stack([u[3] for u in updates])
        if "table" in (kv_cache or {}):
            cache["table"] = kv_cache["table"]   # paged: table rides along
        return cache

    if return_moe_aux:
        # mean balance loss across layers (training regularizer)
        aux = moe_balance / max(cfg.n_layers, 1)
        if kv_cache is not None:
            return out, _pack_cache(), aux
        return out, aux
    if kv_cache is not None:
        return out, _pack_cache()
    return out


def count_params(params: Params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
