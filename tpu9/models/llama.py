"""Llama-3 family configs (baseline configs #2 and #4: 8B on v5e-1, 70B
pjit-TP on v5e-8). Architecture constants follow the public Llama 3 model
cards; weights here are random-initialized (weight porting from safetensors is
a loader concern, tpu9.serving.weights)."""

from __future__ import annotations

from .transformer import DecoderConfig


def llama_config(**kw) -> DecoderConfig:
    base = dict(act="silu", norm_offset=0.0, rope_theta=500000.0,
                norm_eps=1e-5, tie_embeddings=False)
    base.update(kw)
    return DecoderConfig(**base)


LLAMA_PRESETS: dict[str, DecoderConfig] = {
    # test-scale model used by unit tests and the CPU dry-runs
    "llama-tiny": llama_config(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                               n_kv_heads=2, head_dim=32, hidden_dim=256,
                               max_seq_len=512),
    # ~1B config that fits a dev chip for quick perf probes
    "llama-1b": llama_config(vocab_size=128256, dim=2048, n_layers=16,
                             n_heads=32, n_kv_heads=8, head_dim=64,
                             hidden_dim=8192, max_seq_len=8192),
    "llama3-8b": llama_config(vocab_size=128256, dim=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, head_dim=128,
                              hidden_dim=14336, max_seq_len=8192),
    "llama3-70b": llama_config(vocab_size=128256, dim=8192, n_layers=80,
                               n_heads=64, n_kv_heads=8, head_dim=128,
                               hidden_dim=28672, max_seq_len=8192),
}
