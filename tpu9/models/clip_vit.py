"""CLIP vision transformer (baseline config #3: ViT-L/14 image embedding
fan-out across N×v5e-1 task-queue workers).

Encoder-only ViT: conv patch embed (expressed as a reshaped matmul so it hits
the MXU rather than a conv kernel), pre-norm transformer, final layernorm +
projection to the shared embedding space.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclass(frozen=True)
class ClipVisionConfig:
    image_size: int = 224
    patch_size: int = 14
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    hidden_dim: int = 4096
    embed_dim: int = 768           # output projection dim
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CLIP_VIT_L14 = ClipVisionConfig()
CLIP_VIT_TINY = ClipVisionConfig(image_size=28, patch_size=14, dim=64,
                                 n_layers=2, n_heads=4, hidden_dim=128,
                                 embed_dim=32)


def _dense(rng, i, o, dtype):
    return (jax.random.normal(rng, (i, o), dtype=jnp.float32)
            * (2.0 / (i + o)) ** 0.5).astype(dtype)


def init_clip_vision(rng: jax.Array, cfg: ClipVisionConfig) -> Params:
    rngs = jax.random.split(rng, cfg.n_layers * 6 + 4)
    it = iter(rngs)
    dt = cfg.dtype
    patch_dim = 3 * cfg.patch_size * cfg.patch_size
    params: Params = {
        "patch_proj": _dense(next(it), patch_dim, cfg.dim, dt),
        "cls_token": jnp.zeros((1, 1, cfg.dim), dtype=dt),
        "pos_embed": (jax.random.normal(next(it), (cfg.n_patches + 1, cfg.dim),
                                        dtype=jnp.float32) * 0.02).astype(dt),
        "ln_pre": {"scale": jnp.ones((cfg.dim,), jnp.float32),
                   "bias": jnp.zeros((cfg.dim,), jnp.float32)},
        "ln_post": {"scale": jnp.ones((cfg.dim,), jnp.float32),
                    "bias": jnp.zeros((cfg.dim,), jnp.float32)},
        "proj": _dense(next(it), cfg.dim, cfg.embed_dim, dt),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "ln1": {"scale": jnp.ones((cfg.dim,), jnp.float32),
                    "bias": jnp.zeros((cfg.dim,), jnp.float32)},
            "ln2": {"scale": jnp.ones((cfg.dim,), jnp.float32),
                    "bias": jnp.zeros((cfg.dim,), jnp.float32)},
            "wqkv": _dense(next(it), cfg.dim, 3 * cfg.dim, dt),
            "wo": _dense(next(it), cfg.dim, cfg.dim, dt),
            "w1": _dense(next(it), cfg.dim, cfg.hidden_dim, dt),
            "w2": _dense(next(it), cfg.hidden_dim, cfg.dim, dt),
        })
    return params


def _layer_norm(x, p, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * p["scale"]
            + p["bias"]).astype(x.dtype)


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, H, W, 3] -> [B, n_patches, patch*patch*3] (row-major patches)."""
    b, h, w, c = images.shape
    gh, gw = h // patch, w // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def clip_vision_forward(params: Params, images: jnp.ndarray,
                        cfg: ClipVisionConfig) -> jnp.ndarray:
    """images [B, H, W, 3] (f32 0..1) → L2-normalized embeddings [B, embed_dim]."""
    b = images.shape[0]
    x = patchify(images.astype(jnp.float32), cfg.patch_size).astype(cfg.dtype)
    x = x @ params["patch_proj"]
    cls = jnp.broadcast_to(params["cls_token"], (b, 1, cfg.dim))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]
    x = _layer_norm(x, params["ln_pre"], cfg.norm_eps)

    for layer in params["layers"]:
        h = _layer_norm(x, layer["ln1"], cfg.norm_eps)
        qkv = (h @ layer["wqkv"]).reshape(b, -1, 3, cfg.n_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scale = cfg.head_dim ** -0.5
        logits = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale,
                            k.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
        attn = attn.astype(x.dtype).reshape(b, -1, cfg.dim)
        x = x + attn @ layer["wo"]
        h = _layer_norm(x, layer["ln2"], cfg.norm_eps)
        x = x + jax.nn.gelu(h @ layer["w1"], approximate=True) @ layer["w2"]

    cls_out = _layer_norm(x[:, 0], params["ln_post"], cfg.norm_eps)
    emb = (cls_out @ params["proj"]).astype(jnp.float32)
    return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True).clip(1e-6)
