"""Engine flight recorder: a bounded ring of per-window serve-loop records.

The serving hot path (windows, speculative verify, paged-KV growth) is
invisible to logs — logging per window would be noise, logging per token
would be suicide. The flight recorder is the black box instead: every
dispatched ``_Window`` (and every admission) appends ONE plain dict at
host-processing time, built exclusively from state the loop already holds
on the host (monotonic clocks, numpy masks, allocator counters). No device
syncs beyond the existing window-boundary ones, no per-token records.

Record schema (kind == "decode" | "verify"):

    seq               monotonically increasing record id (per engine)
    ts                wall anchor at host processing (merge/display only)
    kind, k           window kind and device steps (verify: 1 + spec_len)
    pick              why this K was picked ("admission" = shrunk to K=1
                      for an imminent admission, else "budget"/"max")
    batch             active slots at dispatch
    slots             {slot: request_id} snapshot at dispatch
    tokens            {slot: tokens delivered} (host fan-out outcome)
    wait_s            dispatch → host processing (device compute + the
                      one-window overlap the loop deliberately holds)
    host_s            host fan-out time for this window's processing
    spec_proposed / spec_accepted / spec_rollback   (verify windows)
    kv_used/kv_free/kv_reserved                     allocator at dispatch
    kv_alloc          blocks allocated since the previous record
    prefix_evictions  prefix-cache evictions since the previous record
    prefix_pinned     currently pinned prefix-cache entries

Admission records (kind == "admit"): request_id, prompt_tokens,
cached_tokens (prefix-cache reuse), chunks, interleaved (decode windows
dispatched during the admission), dur_s.

Profile records (kind == "profile"): armed/stopped markers with the dump
path, so the flight timeline shows which windows a ``jax.profiler`` dump
covers.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Optional


class FlightRecorder:
    """Bounded ring of plain-dict records; query by tail or by seq."""

    def __init__(self, cap: int = 256):
        self.cap = cap
        self._ring: collections.deque[dict] = collections.deque(maxlen=cap)
        self._seq = itertools.count(1)
        self.recorded = 0           # lifetime count (dropped = recorded - len)

    def record(self, kind: str, **fields) -> dict:
        rec = {"seq": next(self._seq), "ts": round(time.time(), 6),
               "kind": kind, **fields}
        self._ring.append(rec)
        self.recorded += 1
        return rec

    def snapshot(self, limit: int = 256, since_seq: int = 0) -> list[dict]:
        """Newest-last tail of the ring: up to ``limit`` records with
        ``seq > since_seq`` (pass the last seen seq to poll incrementally
        without re-reading the whole ring)."""
        out = []
        for rec in reversed(self._ring):
            if rec["seq"] <= since_seq:
                break
            out.append(rec)
            if len(out) >= max(limit, 1):
                break
        out.reverse()
        return out

    def summary(self) -> dict:
        last = self._ring[-1] if self._ring else None
        return {"records": len(self._ring), "cap": self.cap,
                "recorded": self.recorded,
                "dropped": self.recorded - len(self._ring),
                "last_seq": last["seq"] if last else 0}


def maybe(cap: int) -> Optional[FlightRecorder]:
    """Recorder or None — the engine's hot path gates on ``is not None``,
    so a disabled recorder costs one attribute check per window."""
    return FlightRecorder(cap) if cap > 0 else None
