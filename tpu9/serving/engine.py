"""LLM inference engine: continuous batching over jitted prefill/decode.

TPU-first rationale: the engine compiles exactly two graphs per shape bucket —
``prefill(tokens[1, Tpad])`` and ``decode(tokens[B,1])`` — and keeps the KV
cache as a persistent on-device buffer donated through every decode step, so
steady-state decoding is one fused XLA computation per token across the whole
batch with zero host↔device traffic except the sampled ids.

Slots: fixed max_batch decode lanes. New requests prefill (bucketed lengths to
bound compile count), then join the decode batch at their slot index. This is
the same admission shape the reference's LLM-aware pod router assumes
(``pkg/abstractions/pod/llm.go`` token-pressure/active-streams), which the
gateway reads from the engine's ``stats()``.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (DecoderConfig, decoder_forward,
                                  init_kv_cache)
from ..ops.sampling import sample_logits

Params = dict[str, Any]


@dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8
    max_seq_len: int = 2048
    prefill_buckets: tuple = (128, 512, 2048)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1              # -1 disables EOS stopping
    # decode-window buckets: K steps run on-device (lax.scan) per host
    # sync. Each host↔device round-trip costs wall-clock (dramatically so
    # over a TPU relay), so the loop amortizes it over K tokens; K drops
    # to 1 whenever requests wait for admission.
    decode_steps: tuple = (1, 4, 16)


@dataclass
class _Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    done: asyncio.Event = field(default_factory=asyncio.Event)
    queue: Optional[asyncio.Queue] = None   # set for streaming requests


class InferenceEngine:
    """Continuous-batching engine around a decoder model."""

    def __init__(self, params: Params, cfg: DecoderConfig,
                 engine_cfg: EngineConfig = EngineConfig()):
        self.params = params
        self.cfg = cfg
        self.ecfg = engine_cfg
        b, s = engine_cfg.max_batch, engine_cfg.max_seq_len
        self.kv_cache = init_kv_cache(cfg, b, s)
        self.cache_len = jnp.zeros((b,), jnp.int32)     # valid prefix per slot
        self.active = np.zeros((b,), dtype=bool)
        self.slot_req: list[Optional[_Request]] = [None] * b
        self.last_token = jnp.zeros((b, 1), jnp.int32)
        self._rng = jax.random.PRNGKey(0)
        self._queue: asyncio.Queue[_Request] = asyncio.Queue()
        self._loop_task: Optional[asyncio.Task] = None
        self._compiled: dict[Any, Any] = {}
        self._host_len = np.zeros((b,), dtype=np.int64)  # host mirror of
        # cache_len — the loop must not pay a device round-trip to know room
        self._stats = {"active_streams": 0, "queued": 0, "tokens_generated": 0,
                       "decode_steps": 0}

    # -- compiled steps ------------------------------------------------------

    def _build_decode(self, k: int = 1):
        cfg, ecfg = self.cfg, self.ecfg

        def one_step(params, kv_cache, last_token, cache_len, active, rng):
            positions = cache_len[:, None]              # next position per slot
            logits, kv_cache = decoder_forward(
                params, last_token, cfg, positions=positions,
                kv_cache=kv_cache, cache_len=cache_len + 1, decode=True)
            rng, sub = jax.random.split(rng)
            next_tok = sample_logits(logits[:, -1], sub,
                                     temperature=ecfg.temperature,
                                     top_k=ecfg.top_k, top_p=ecfg.top_p)
            # only live slots advance; idle lanes stay parked at 0 so the
            # token-pressure signal reflects real cache occupancy
            new_len = cache_len + active.astype(jnp.int32)
            return next_tok[:, None].astype(jnp.int32), kv_cache, new_len, rng

        def decode(params, kv_cache, last_token, cache_len, active, rng):
            def body(carry, _):
                last, kv, clen, r = carry
                last, kv, clen, r = one_step(params, kv, last, clen,
                                             active, r)
                return (last, kv, clen, r), last[:, 0]

            (last, kv_cache, cache_len, rng), toks = jax.lax.scan(
                body, (last_token, kv_cache, cache_len, rng), None,
                length=k)
            # toks [k, B]: the host consumes the whole window in one sync
            return last, kv_cache, cache_len, rng, toks

        return jax.jit(decode, donate_argnums=(1,))

    def _decode_k(self, k: int):
        key = ("decode", k)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._compiled[key] = self._build_decode(k)
        return fn

    def _pick_steps(self) -> int:
        """Largest decode-window bucket every active slot can absorb: no
        slot may outrun its max_new_tokens budget past the window (tokens
        beyond a stop are discarded host-side, so only bounded compute is
        wasted) nor its cache room. Admission latency wins when work is
        queued: K=1."""
        if not self._queue.empty():
            return self.ecfg.decode_steps[0]
        limit = max(self.ecfg.decode_steps)
        for slot in range(self.ecfg.max_batch):
            req = self.slot_req[slot]
            if req is None or not self.active[slot]:
                continue
            remaining = req.max_new_tokens - len(req.generated)
            room = self.ecfg.max_seq_len - 1 - self._host_len[slot]
            limit = min(limit, max(1, remaining), max(1, room))
        for k in reversed(self.ecfg.decode_steps):
            if k <= limit:
                return k
        return self.ecfg.decode_steps[0]

    def _prefill_fn(self, bucket: int):
        if bucket in self._compiled:
            return self._compiled[bucket]
        cfg = self.cfg

        def prefill(params, tokens, length):
            # tokens [1, bucket] padded; returns logits at the last real token
            # and the per-layer k/v for the prefix.
            logits, cache = decoder_forward(
                params, tokens, cfg,
                kv_cache=init_kv_cache(cfg, 1, bucket), decode=False)
            last = logits[0, length - 1]
            return last, cache

        fn = jax.jit(prefill)
        self._compiled[bucket] = fn
        return fn

    def _bucket_for(self, n: int) -> int:
        for b in self.ecfg.prefill_buckets:
            if n <= b:
                return b
        return self.ecfg.prefill_buckets[-1]

    # -- public API ----------------------------------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._serve_loop())

    def warmup(self) -> dict:
        """Precompile every prefill bucket and decode-window graph.

        Production engines pay XLA compiles at boot, not on the first user
        request: an 8B decode graph takes ~10 s to compile, and a window
        size that first occurs mid-traffic (e.g. K=1 when retirements
        stagger) would stall the whole decode batch behind a compile. Runs
        each graph once with all-inactive lanes (state is threaded back, so
        this is a no-op for correctness) and fences with a device→host copy.
        """
        import time as _time
        timings: dict[str, float] = {}
        for bucket in self.ecfg.prefill_buckets:
            t0 = _time.perf_counter()
            tokens = jnp.zeros((1, bucket), jnp.int32)
            last, _cache = self._prefill_fn(bucket)(self.params, tokens, 1)
            np.asarray(jax.device_get(last[:4]))
            timings[f"prefill_{bucket}_s"] = _time.perf_counter() - t0
        inactive = jnp.zeros((self.ecfg.max_batch,), bool)
        for k in self.ecfg.decode_steps:
            t0 = _time.perf_counter()
            (self.last_token, self.kv_cache, self.cache_len, self._rng,
             toks) = self._decode_k(k)(
                self.params, self.kv_cache, self.last_token,
                self.cache_len, inactive, self._rng)
            np.asarray(jax.device_get(toks[-1, :4]))
            timings[f"decode_k{k}_s"] = _time.perf_counter() - t0
        return timings

    async def stop(self) -> None:
        if self._loop_task:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None

    async def generate(self, prompt: list[int], max_new_tokens: int = 32,
                       request_id: str = "", stream: bool = False):
        limit = min(self.ecfg.prefill_buckets[-1], self.ecfg.max_seq_len - 1)
        if len(prompt) > limit:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine limit {limit}")
        if not prompt:
            raise ValueError("empty prompt")
        req = _Request(request_id=request_id or f"r{time.monotonic_ns()}",
                       prompt=list(prompt), max_new_tokens=max_new_tokens,
                       queue=asyncio.Queue() if stream else None)
        await self._queue.put(req)
        self._stats["queued"] = self._queue.qsize()
        if stream:
            return req  # caller iterates req.queue
        await req.done.wait()
        return req.generated

    def stats(self) -> dict:
        out = dict(self._stats)
        out["active_streams"] = int(self.active.sum())
        out["queued"] = self._queue.qsize()
        out["token_pressure"] = float(
            np.asarray(jax.device_get(self.cache_len)).sum()
            / (self.ecfg.max_batch * self.ecfg.max_seq_len))
        return out

    # -- engine loop ---------------------------------------------------------

    def _admit(self, req: _Request, slot: int):
        """Prefill + cache splice for one request. Returns the slot's
        first-token DEVICE value — the serve loop syncs a whole admission
        batch in one host round-trip (each blocking ``int()`` here would
        cost a full RTT, brutal over a TPU relay)."""
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, :n] = req.prompt[:bucket]
        last, cache = self._prefill_fn(bucket)(
            self.params, jnp.asarray(tokens), n)
        # copy prefix cache into the slot's lanes
        k = self.kv_cache["k"]
        v = self.kv_cache["v"]
        k = jax.lax.dynamic_update_slice(
            k, cache["k"][:, :, :bucket], (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            v, cache["v"][:, :, :bucket], (0, slot, 0, 0, 0))
        self.kv_cache = {"k": k, "v": v}
        self.cache_len = self.cache_len.at[slot].set(n)
        self._host_len[slot] = n
        # sample the first generated token from the prefill logits
        self._rng, sub = jax.random.split(self._rng)
        first = sample_logits(last, sub, temperature=self.ecfg.temperature,
                              top_k=self.ecfg.top_k, top_p=self.ecfg.top_p)
        self.last_token = self.last_token.at[slot, 0].set(first)
        req.slot = slot
        self.active[slot] = True
        self.slot_req[slot] = req
        return first

    def _deliver_first(self, req: _Request, first: int) -> None:
        req.generated.append(first)
        if req.queue is not None:
            req.queue.put_nowait(first)
        # the prefill-sampled token may already satisfy the stop conditions
        if (req.max_new_tokens <= 1
                or (self.ecfg.eos_id >= 0 and first == self.ecfg.eos_id)):
            self._retire(req.slot)

    def _retire(self, slot: int) -> None:
        req = self.slot_req[slot]
        self.active[slot] = False
        self.slot_req[slot] = None
        self.cache_len = self.cache_len.at[slot].set(0)
        self._host_len[slot] = 0
        if req is not None:
            if req.queue is not None:
                req.queue.put_nowait(None)
            req.done.set()

    async def _serve_loop(self) -> None:
        while True:
            # admit as many queued requests as there are free slots; ALL
            # their first tokens sync in one device round-trip at the end
            pending: list[tuple[_Request, Any]] = []
            while not self._queue.empty() and not self.active.all():
                req = self._queue.get_nowait()
                slot = int(np.argmin(self.active))
                pending.append((req, self._admit(req, slot)))

            if not self.active.any() and not pending:
                # idle: block for work
                req = await self._queue.get()
                pending.append((req, self._admit(req, 0)))

            if pending:
                firsts = np.asarray(jax.device_get(
                    jnp.stack([f for _, f in pending])))
                for (req, _), first in zip(pending, firsts):
                    self._deliver_first(req, int(first))

            if not self.active.any():
                continue

            # one decode WINDOW for the whole batch: k steps on-device,
            # one host sync for all k×B tokens
            k = self._pick_steps()
            (self.last_token, self.kv_cache,
             self.cache_len, self._rng, toks) = self._decode_k(k)(
                self.params, self.kv_cache, self.last_token,
                self.cache_len, jnp.asarray(self.active), self._rng)
            self._stats["decode_steps"] += k
            window = np.asarray(jax.device_get(toks))        # [k, B]
            for step in range(k):
                for slot in range(self.ecfg.max_batch):
                    if not self.active[slot]:
                        continue
                    req = self.slot_req[slot]
                    tok = int(window[step, slot])
                    req.generated.append(tok)
                    self._host_len[slot] += 1
                    self._stats["tokens_generated"] += 1
                    if req.queue is not None:
                        req.queue.put_nowait(tok)
                    hit_eos = (self.ecfg.eos_id >= 0
                               and tok == self.ecfg.eos_id)
                    # prompt + generated must fit the cache
                    out_of_room = (self._host_len[slot]
                                   >= self.ecfg.max_seq_len - 1)
                    if (len(req.generated) >= req.max_new_tokens or hit_eos
                            or out_of_room):
                        # remaining window tokens for this slot are noise
                        # (the device kept decoding); retire discards them
                        # by flipping active off — the cache lanes reset
                        self._retire(slot)
            # yield to the event loop so new requests can land
            await asyncio.sleep(0)
