"""LLM inference engine: continuous batching over jitted prefill/decode.

TPU-first rationale: the engine compiles a small fixed set of graphs per
shape bucket — ``prefill(tokens[1, Tpad])``, ``decode(tokens[B,1])`` windows
(k steps per host sync) and, with speculation on, ``verify(tokens[B,1+s])``
(prompt-lookup drafts checked in ONE batched forward, ISSUE 5) — and keeps
the KV cache as a persistent on-device buffer donated through every step, so
steady-state decoding is one fused XLA computation per WINDOW across the
whole batch with zero host↔device traffic except the sampled ids.

Slots: fixed max_batch decode lanes. New requests prefill (bucketed lengths to
bound compile count), then join the decode batch at their slot index. This is
the same admission shape the reference's LLM-aware pod router assumes
(``pkg/abstractions/pod/llm.go`` token-pressure/active-streams), which the
gateway reads from the engine's ``stats()``.

Decomposition (ISSUE 9): this module is the serve LOOP — admission,
window dispatch/fan-out, request lifecycle, observability. The three
split-off responsibilities live next door with an explicit boundary
(BND001 contracts in ``tpu9/analysis/boundaries.toml``):

- :mod:`tpu9.serving.graphs`   — every traced/compiled XLA computation
- :mod:`tpu9.serving.schedule` — window-size / spec-gate decisions
- :mod:`tpu9.serving.kvpool`   — paged-pool sizing + block bookkeeping
- :mod:`tpu9.serving.shard`    — the sharding POLICY all device placement
  goes through: ``topology 1x1`` is the identity (this engine, verbatim,
  bit-identical graphs); ``tp×fsdp`` shards weights and the KV pool's
  head axis across a submesh while everything host-side here stays
  topology-oblivious (block ids are global; only resident layout shards).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import DecoderConfig, init_kv_cache
from ..observability.metrics import Metrics
from ..observability.trace import tracer
from ..ops.sampling import sample_logits
from ..utils.aio import reap
from .flight import maybe as flight_maybe
from .graphs import GraphFactory
from .schedule import WindowScheduler

Params = dict[str, Any]

# deadline-expiry error prefix (ISSUE 15). This string is a WIRE contract:
# the llm runner maps it to 504 and the gateway's failover classifier
# treats it as final (the budget is spent — retrying would burn chips on
# an answer the client stopped waiting for). Keep in sync with
# tpu9.gateway.survival.DEADLINE_ERROR (the boundary map forbids a
# shared import in either direction).
DEADLINE_ERROR = "deadline_exceeded"


def abstract_params(tree: Any) -> Any:
    """Pytree of arrays (or ShapeDtypeStructs) → matching
    ``jax.ShapeDtypeStruct`` tree. The compile-ahead contract: everything
    :meth:`InferenceEngine.precompile` needs from the weights is this."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


@dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8
    max_seq_len: int = 2048
    prefill_buckets: tuple = (128, 512, 2048)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1              # -1 disables EOS stopping
    # decode-window buckets: K steps run on-device (lax.scan) per host
    # sync. Each host↔device round-trip costs wall-clock (dramatically so
    # over a TPU relay), so the loop amortizes it over K tokens; K drops
    # to 1 whenever requests wait for admission.
    decode_steps: tuple = (1, 4, 16)
    # ---- paged KV (VERDICT r03 #5) ----
    # block size of the shared KV pool; 0 = legacy dense [B, S] cache
    kv_block_size: int = 0
    # pool size in blocks; 0 = auto (max_batch * max_seq/block — dense
    # parity). Set lower to BOUND KV memory: admission then reserves
    # against it and queues when full.
    kv_pool_blocks: int = 0
    # chunked-prefill chunk length (paged mode); long prompts compile
    # ONE (C, S) graph instead of a full-length bucket. 0 = auto (=
    # smallest prefill bucket).
    prefill_chunk: int = 0
    # pool blocks the engine-level prefix cache may hold for KV reuse
    # across requests sharing a prompt prefix; 0 disables
    prefix_cache_blocks: int = 0
    # "int8" stores the paged KV pool as int8 with per-(position, head)
    # f32 absmax scales alongside it (ISSUE 6): writes quantize, the
    # decode/verify attention dequantizes in-kernel, and with
    # kv_pool_blocks=0 (auto) the pool is sized to the SAME HBM bytes the
    # bf16 pool would have used — i.e. ~2x the blocks, which is directly
    # more admission headroom (reservations, router kv_blocks signal).
    # Requires the paged engine ("" = full-precision pool).
    kv_quant: str = ""
    # chunks per fused admission dispatch (VERDICT r04 #6): a group of G
    # chunks runs as ONE lax.scan graph (chunk prefill + block splice
    # fused), and the serve loop interleaves a decode window between
    # groups so a long admission doesn't starve the decode batch.
    # 1 = one dispatch per chunk (legacy shape, still no per-chunk sync)
    admit_group_chunks: int = 4
    # ---- speculative decoding (ISSUE 5) ----
    # max draft tokens per verify window (prompt-lookup n-gram drafts,
    # tpu9/serving/spec.py); 0 disables speculation. One batched forward
    # verifies [B, 1+spec_len] positions — in the bandwidth-bound decode
    # regime that pass costs ~one decode step of HBM traffic, so every
    # accepted draft token is nearly free.
    spec_len: int = 0
    # acceptance-EWMA floor (mean EFFECTIVE acceptance over active slots,
    # non-proposing slots counting 0): below it the serve loop falls back
    # to classic windowed decode so adversarial prompts never regress
    # past a probe's worth of wasted verify compute. The measured CPU
    # break-even for spec_len=8 is ~0.25 (verify ≈ 2.6-3 decode steps);
    # the floor sits above it so the gate only admits windows that WIN,
    # not ones that tread water while paying scheduling overhead. On TPU
    # the bandwidth-bound verify is ~1 step, so the floor is conservative
    spec_min_accept: float = 0.35
    # after auto-disable, force one speculative window every N classic
    # windows regardless of the EWMA. 0 (default) disables forced probes:
    # classic windows SHADOW-SCORE the proposer against their own output
    # (see _Window.shadow), so the EWMA recovers for free the moment a
    # stream turns repetitive — blind probe windows would only burn
    # verify compute re-learning what the shadows already measured
    spec_probe_every: int = 0
    # ---- KV tiering (ISSUE 20) ----
    # host-DRAM second tier for the paged KV pool, in MB; 0 disables
    # tiering entirely (the pool is bit-identical to the untiered one).
    # TPU9_KV_HOST_POOL_MB overrides at engine construction, and the
    # TPU9_KV_TIER master gate can force tiering off regardless.
    kv_host_pool_mb: int = 0
    # ---- observability (ISSUE 8) ----
    # flight-recorder ring capacity, in records (one per dispatched window
    # or admission — never per token). 0 disables the recorder entirely;
    # the hot path then pays one `is not None` check per window.
    flight_cap: int = 256


@dataclass
class _Window:
    """One dispatched decode/verify window whose host fan-out is deferred:
    the device arrays are fetched later (one transfer per drain) so host
    work overlaps device compute. ``mask``/``reqs`` snapshot the active
    set AT DISPATCH — a window must deliver tokens only to the exact
    request that occupied the slot when it was dispatched (a slot retired
    and re-admitted while the window was in flight gets nothing)."""
    kind: str                 # "decode" | "verify"
    k: int                    # device steps (decode k, or 1 + spec_len)
    toks: Any                 # device [k, B] (decode) / [B, k] (verify)
    mask: Any                 # np active snapshot at dispatch
    reqs: tuple               # slot_req snapshot at dispatch
    n_acc: Any = None         # device [B] (verify): accepted drafts/slot
    spec_len: int = 0
    n_real: Any = None        # np [B] (verify): real (non-pad) drafts
    # observability (ISSUE 8): monotonic/wall anchor pair captured at
    # dispatch (durations from monotonic, merge timelines from wall), why
    # this K was picked, allocator snapshot at dispatch, and the host
    # fan-out outcome (tokens delivered per live slot) filled in during
    # processing — everything the flight record and the per-request
    # decode-window spans need, with zero extra device syncs
    t_mono: float = 0.0
    t_wall: float = 0.0
    pick: str = ""
    kv_snap: tuple = ()       # (used, free, reserved) at dispatch (paged)
    delivered: Any = None     # {slot: tokens delivered} (host processing)
    spec_stats: tuple = ()    # (proposed, accepted) (verify processing)


@dataclass
class _Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    done: asyncio.Event = field(default_factory=asyncio.Event)
    queue: Optional[asyncio.Queue] = None   # set for streaming requests
    error: str = ""
    cancelled: bool = False                 # client abandoned the request
    # request deadline (ISSUE 15): monotonic stamp past which the request
    # must not be prefilled and a mid-decode slot is retired (0 = none)
    deadline_mono: float = 0.0
    # observability (ISSUE 8): remote trace context (trace_id, parent
    # span id) carried across the runner RPC boundary; span is the
    # engine.request span opened at admission under that parent
    trace: Optional[tuple] = None
    span: Any = None
    span_id: str = ""    # survives _obs_done so the window that RETIRES a
    #                      request can still parent its decode_window span
    t_enqueue_mono: float = 0.0
    t_enqueue_wall: float = 0.0
    t_first_mono: float = 0.0               # first token delivered
    admit_cached: int = 0                   # prefix-cache tokens reused
    admit_chunks: int = 0                   # prefill chunks dispatched


class InferenceEngine:
    """Continuous-batching engine around a decoder model."""

    def __init__(self, params: Params, cfg: DecoderConfig,
                 engine_cfg: EngineConfig = EngineConfig(),
                 policy=None):
        self.cfg = cfg
        self.ecfg = engine_cfg
        # sharding policy (ISSUE 9): ALL device placement below routes
        # through it. None → the single-device identity policy, which
        # makes this constructor byte-for-byte the pre-split engine.
        if policy is None:
            from .shard.policy import SingleDevicePolicy
            policy = SingleDevicePolicy()
        self.policy = policy
        # weights route through the policy HERE, not just in load_engine —
        # a mesh engine handed raw host params would otherwise serve
        # replicated weights (all the HBM, none of the sharding) the first
        # time XLA implicitly places them. Identity for 1x1; a no-op
        # device_put for already-placed trees. Compile-ahead constructs
        # with abstract ShapeDtypeStruct trees that cannot be placed —
        # bind_params places the real arrays later.
        leaves = jax.tree_util.tree_leaves(params)
        if leaves and not any(isinstance(x, jax.ShapeDtypeStruct)
                              for x in leaves):
            params = policy.place_params(params)
        self.params = params
        topo = policy.describe()
        if topo["tp"] > 1 and cfg.n_kv_heads % topo["tp"]:
            # fit_spec would silently REPLICATE the KV head axis (all the
            # HBM cost, none of the capacity win) while feasibility priced
            # the gcd shard — the exact OOM the deploy gate exists to
            # prevent. The planner only emits dividing tp; an explicit
            # override that doesn't divide must fail loudly at bind time.
            raise ValueError(
                f"topology tp={topo['tp']} does not divide n_kv_heads="
                f"{cfg.n_kv_heads} — the paged-KV head axis cannot shard "
                "evenly. Use a tp that divides the KV heads (put excess "
                "chips on fsdp, e.g. 'tp=2,fsdp=2') or topology='auto'")
        b, s = engine_cfg.max_batch, engine_cfg.max_seq_len
        self.paged = engine_cfg.kv_block_size > 0
        from ..ops.quant import validate_quant_mode
        _kvq = validate_quant_mode(engine_cfg.kv_quant, "kv_quant")
        if _kvq and _kvq != "int8":
            # a mode added to SUPPORTED_MODES but not wired here must
            # fail, not silently serve a full-precision pool the caller
            # sized admission/HBM around
            raise NotImplementedError(
                f"kv_quant mode {_kvq!r} is not wired into the engine")
        self.kv_quant = _kvq == "int8"
        if self.kv_quant and not self.paged:
            raise ValueError("kv_quant='int8' requires the paged engine "
                             "(kv_block_size > 0)")
        if self.paged:
            from .kvpool import KvPool
            bs = engine_cfg.kv_block_size
            if s % bs:
                raise ValueError(f"max_seq_len {s} % kv_block_size {bs}")
            chunk = engine_cfg.prefill_chunk \
                or min(engine_cfg.prefill_buckets)
            if chunk % bs:
                # a chunk smaller than a block would make the splice a
                # silent no-op (nb = chunk//bs = 0) and every token would
                # decode against zero-filled prompt KV
                raise ValueError(
                    f"prefill_chunk {chunk} must be a multiple of "
                    f"kv_block_size {bs}")
            if s % chunk:
                # with S % C != 0 the final chunk of a long prompt starts
                # at an offset where offset + C > S; dynamic_update_slice
                # CLAMPS the write start backwards, silently overwriting
                # valid prefix KV (advisor r04). Reject loudly instead.
                raise ValueError(
                    f"max_seq_len {s} must be a multiple of "
                    f"prefill_chunk {chunk}")
            self._chunk = chunk     # the validated value IS the used value
            # pool sizing + trash-block + slot/block bookkeeping: the
            # split-off KV-pool manager (serving.kvpool). The aliases
            # below are the SAME objects, kept so the admission/retire
            # paths (and tests/bench) read the state where it always was.
            # host-DRAM tier (ISSUE 20): EngineConfig field, env
            # override, master gate — all resolved here so 0 MB keeps
            # the pool bit-identical to the untiered build
            from ..config import env_kv_host_pool_mb, env_kv_tier_on
            host_mb = env_kv_host_pool_mb(engine_cfg.kv_host_pool_mb)
            if not env_kv_tier_on() or engine_cfg.prefix_cache_blocks <= 0:
                host_mb = 0
            self.pool = KvPool(cfg, engine_cfg, self.kv_quant, policy,
                               host_pool_mb=host_mb)
            self.kv_cache = self.pool.init_arrays()
            self.allocator = self.pool.allocator
            self.prefix_cache = self.pool.prefix_cache
            self._slot_blocks = self.pool.slot_blocks
            self._slot_reserved = self.pool.slot_reserved
            self._table_np = self.pool.table_np
            self._trash_block = self.pool.trash_block
            self._mb = self.pool.mb
            # batch-1 dense scratch the chunked prefill writes through
            # before splicing into pool blocks — ONE lane, not B of them
            self._scratch = policy.place_kv(init_kv_cache(cfg, 1, s))
        else:
            self.pool = None
            self.kv_cache = policy.place_kv(init_kv_cache(cfg, b, s))
            self.allocator = None
            self.prefix_cache = None
        # every traced/compiled graph lives in the factory (serving.graphs)
        self.graphs = GraphFactory(cfg, engine_cfg, policy,
                                   chunk=self._chunk if self.paged else 0,
                                   kv_quant=self.kv_quant)
        self.scheduler = WindowScheduler(self)
        self._buckets = sorted({min(bk, s)
                                for bk in engine_cfg.prefill_buckets})
        self.cache_len = jnp.zeros((b,), jnp.int32)     # valid prefix per slot
        self.active = np.zeros((b,), dtype=bool)
        self.slot_req: list[Optional[_Request]] = [None] * b
        self.last_token = jnp.zeros((b, 1), jnp.int32)
        self._rng = jax.random.PRNGKey(0)
        self._queue: asyncio.Queue[_Request] = asyncio.Queue()
        self._loop_task: Optional[asyncio.Task] = None
        self._dead_reason: Optional[str] = None   # loop died: fail fast
        self._admitting: Optional[_Request] = None
        # paged admission parks over-budget requests here; dense mode
        # keeps it empty (shared so failure fan-out/cancel need no mode
        # branches)
        self._wait_room: list[_Request] = []
        # host-tier up-pages in flight, keyed by prefix key: concurrent
        # admissions hitting the same host entry await the first up-page
        # instead of double-filling fresh blocks (ISSUE 20)
        self._uppage_inflight: dict = {}
        # the compiled-graph cache lives in the factory; alias for the
        # bench/diagnostic surface that predates the split
        self._compiled = self.graphs.compiled
        self._host_len = np.zeros((b,), dtype=np.int64)  # host mirror of
        # cache_len — the loop must not pay a device round-trip to know room
        # windows dispatched but not yet host-processed (_Window records):
        # admission-interleaved decode windows AND the steady-state
        # in-flight window both ride here; room accounting must include
        # their steps (_inflight_steps)
        self._deferred_windows: list[_Window] = []
        self._inflight_steps = 0
        # ---- speculative decoding (ISSUE 5) ----
        # verify-graph length buckets (each is one compiled graph). A
        # single full-size bucket: on the paged path the verify cost is
        # gather-dominated, so a half-size bucket costs the same and can
        # never pay — adaptivity lives in the effective-acceptance gate
        # (_spec_gate), not in shrinking the graph
        self._spec_lens: tuple = (
            (engine_cfg.spec_len,) if engine_cfg.spec_len > 0 else ())
        self._spec_slots: list = [None] * b   # per-slot SlotSpecState
        self._spec_disabled_windows = 0
        self._stats = {"active_streams": 0, "queued": 0, "tokens_generated": 0,
                       "decode_steps": 0, "admit_dispatches": 0,
                       "admit_interleaved_windows": 0,
                       "spec_windows": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "deadline_expired": 0,
                       # kvwire (ISSUE 16): block-ship accounting — flat
                       # so the runner heartbeat forwards them unchanged
                       "kvwire_exports": 0, "kvwire_export_misses": 0,
                       "kvwire_blocks_exported": 0,
                       "kvwire_bytes_exported": 0,
                       "kvwire_blocks_imported": 0,
                       "kvwire_bytes_imported": 0,
                       "kvwire_import_hits": 0,
                       "kvwire_import_fallbacks": 0,
                       # kv tiering (ISSUE 20): paging + recompute
                       # accounting, flat for the heartbeat like kvwire
                       "kvtier_downpages": 0, "kvtier_uppages": 0,
                       "kvtier_uppage_failures": 0,
                       "kvtier_peer_spills": 0}
        # ---- observability (ISSUE 8) ----
        # flight recorder: bounded per-window ring (None = disabled)
        self.flight = flight_maybe(engine_cfg.flight_cap)
        # bring-up decomposition (ISSUE 13): load/compile_ahead/bind
        # seconds set by presets.load_engine, warmup_s by the runner —
        # stats() forwards them flat so the heartbeat can ship them into
        # the per-replica coldstart record
        self.bringup: dict = {}
        # execute-while-scaling readiness (ISSUE 17): weight groups bound
        # so far vs expected — set via note_group_bound() as the restore
        # streams, forwarded flat (scaleout_*) on the pressure heartbeat
        # so the router can admit per-group before the restore completes.
        # Empty = not a partial bring-up: ready_frac reports 1.0.
        self._scaleout_groups: dict = {"total": 0, "bound": []}
        # per-ENGINE latency registry (TTFT/TBT/queue-wait/prefill/decode
        # windows): its summaries ride stats() → the runner's pressure
        # heartbeat → /api/v1/metrics "engines". A process-global registry
        # would mix engines when two live in one process (bench A/B).
        self.metrics = Metrics()
        self._pick_reason = ""
        self._flight_kv_allocs = 0   # marker for per-record deltas
        # (lifetime allocation counter lives on the KvPool manager)
        self._flight_evictions = 0
        # on-demand jax.profiler hook (/rpc/llm/profile): armed for the
        # next N windows, started/stopped at window boundaries
        self._profile_remaining = 0
        self._profile_active = False
        self._profile_path = ""
        self._profile_error = ""
        # ---- fleet timeline physics (ISSUE 12) ----
        # tokens/sec window: (monotonic, tokens_generated) pairs appended
        # on the stats() READ path (heartbeat cadence), zero serve-loop
        # cost; rate = delta over the retained window
        self._tps_window: list = []
        # per-chip decode physics constants: bytes streamed / matmul
        # FLOPs per generated token, so the CONTROL plane can price
        # MFU/MBU from heartbeated tokens/sec without importing model
        # internals. Decode is weight-streaming-bound: every step reads
        # the whole resident weight shard (KV bytes excluded — second-
        # order for the fleet-utilization signal this feeds).
        n_chips = max(int(self.policy.describe().get("n_chips", 1)), 1)
        wb = nparams = 0
        for leaf in jax.tree_util.tree_leaves(params):
            size = getattr(leaf, "size", 0)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 0)
            wb += size * itemsize
            nparams += size
        self._phys_bytes_per_token_per_chip = wb / n_chips
        self._phys_flops_per_token_per_chip = 2.0 * nparams / n_chips
        try:
            self._device_kind = jax.devices()[0].device_kind
        except Exception:   # noqa: BLE001 — physics labels are best-effort
            self._device_kind = ""
        # ---- replica health plane (ISSUE 14) ----
        # liveness watermark: monotonic progress counters + dispatch/
        # progress stamps the runner-side watchdog classifies from. All
        # stamped on host paths the loop already runs — zero new syncs.
        self._windows_processed = 0
        self._last_dispatch_mono = 0.0
        self._last_progress_mono = time.monotonic()
        # HBM watermarks: live per-chip residency sampled on the stats()
        # READ path (heartbeat cadence) vs the planned residency computed
        # from the exact trees this engine holds — weights shard over
        # tp×fsdp, KV payload over the tp head shard (feasibility.py's
        # arithmetic, priced against the real leaves)
        self._hbm_peak_gb = 0.0
        topo = self.policy.describe()
        kvb = sum(getattr(leaf, "size", 0)
                  * getattr(getattr(leaf, "dtype", None), "itemsize", 0)
                  for leaf in jax.tree_util.tree_leaves(self.kv_cache))
        if self.paged:
            kvb += sum(
                getattr(leaf, "size", 0)
                * getattr(getattr(leaf, "dtype", None), "itemsize", 0)
                for leaf in jax.tree_util.tree_leaves(self._scratch))
        self.hbm_predicted_gb_per_chip = round(
            (wb / max(topo["tp"] * topo["fsdp"], 1)
             + kvb / max(topo["tp"], 1)) / 1e9, 3)
        # chip capacity is hardware-constant: sweep memory_stats() for it
        # ONCE here, not on every stats() read (the live-usage sweep is
        # the only per-beat memory_stats cost)
        self._hbm_limit_gb = self.policy.hbm_limit_gb_per_chip()
        # black box (ISSUE 14): the serve-loop failure handler snapshots
        # the forensic record HERE before fan-out clears the evidence;
        # the runner ships it to the gateway on the next heartbeat
        self.last_postmortem: Optional[dict] = None

    # -- compiled steps (serving.graphs) + scheduling (serving.schedule) ----
    # Thin delegates: the implementations moved out with the ISSUE 9
    # engine split; these names are the engine's stable internal surface
    # (bench and the spec/paged tests exercise them directly).

    def _decode_k(self, k: int):
        return self.graphs.decode_k(k)

    def _verify_fn(self, s: int):
        return self.graphs.verify_fn(s)

    def _prefill_fn(self, bucket: int):
        return self.graphs.prefill_fn(bucket)

    def _dense_splice_fn(self, bucket: int):
        return self.graphs.dense_splice_fn(bucket)

    def _chunk_fn(self):
        return self.graphs.chunk_fn()

    def _gather_fn(self):
        return self.graphs.gather_fn()

    def _splice_fn(self):
        return self.graphs.splice_fn()

    def _chunk_group_fn(self, g: int):
        return self.graphs.chunk_group_fn(g)

    def _admission_can_proceed(self) -> bool:
        return self.scheduler.admission_can_proceed()

    def _pick_steps(self) -> int:
        return self.scheduler.pick_steps()

    def _spec_room_len(self) -> int:
        return self.scheduler.spec_room_len()

    def _spec_gate(self, s: int) -> int:
        return self.scheduler.spec_gate(s)

    def _bucket_for(self, n: int) -> int:
        # buckets are CLAMPED to max_seq_len: a configured bucket wider
        # than the cache (e.g. default (128,512,2048) with max_seq 1024)
        # would make the splice a trace-time error that kills the loop
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    # -- paged-KV machinery (graphs live in serving.graphs; block/table
    # bookkeeping in serving.kvpool) ----------------------------------------

    def _pool_dict(self) -> dict:
        """The kv pool's array view (payload + scales, no table) — the
        pytree the splice/gather/fused-group graphs take and return."""
        keys = ("k", "v", "k_scale", "v_scale") if self.kv_quant \
            else ("k", "v")
        return {k: self.kv_cache[k] for k in keys}

    def _set_pool(self, pool: dict) -> None:
        self.kv_cache.update(pool)

    def bench_reset_slots(self, ctx0: int, budget: int) -> None:
        """Raw-loop benchmarking support: give every slot physical blocks
        covering [0, ctx0 + budget) so a paged decode window moves the
        same HBM traffic it would in production (an all-zero table would
        read one block B times and fake the bandwidth numbers)."""
        if not self.paged:
            return
        for slot in range(self.ecfg.max_batch):
            if self._slot_blocks[slot]:
                self.allocator.release(self._slot_blocks[slot])
                self._slot_blocks[slot] = []
            self._ensure_slot_blocks(slot, ctx0 + budget + 1)
            self._host_len[slot] = ctx0

    def _worst_case_tokens(self, req: _Request) -> int:
        # prompt + full generation budget + in-flight overshoot slack,
        # clamped to the cache: positions never exceed max_seq_len, so a
        # near-max prompt must not over-reserve itself into rejection.
        # With speculation on, up to TWO verify windows can be in flight
        # past the budget check (the steady-state overlap window plus the
        # one being dispatched), so the slack covers 2·(1+spec_len).
        slack = max(self.ecfg.decode_steps) + 1
        if self._spec_lens:
            slack = max(slack, 2 * (self._spec_lens[-1] + 1) + 1)
        return min(len(req.prompt) + req.max_new_tokens + slack,
                   self.ecfg.max_seq_len)

    def _alloc_blocks(self, n: int) -> list[int]:
        return self.pool.alloc_blocks(n)

    def _push_table(self, slot: int) -> None:
        self.kv_cache["table"] = self.pool.push_table(slot)

    def _ensure_slot_blocks(self, slot: int, n_tokens: int) -> bool:
        """Grow the slot's physical block list to cover ``n_tokens``
        positions. Returns True when the table changed."""
        if not self.pool.ensure_slot_blocks(slot, n_tokens):
            return False
        self._push_table(slot)
        return True

    # -- public API ----------------------------------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._serve_loop())

    def bind_params(self, params: Params) -> None:
        """Swap the engine onto real weights. The compile-ahead path
        constructs the engine with an ABSTRACT param tree
        (``jax.ShapeDtypeStruct`` leaves — see :func:`abstract_params`),
        precompiles while the weights stream, then binds the streamed /
        pooled arrays here. The engine must not serve before this.
        Placement goes through the sharding policy: a mesh engine shards
        the tree per ``decoder_param_specs`` here (already-sharded arrays
        device_put to their own sharding, a no-op)."""
        self.params = self.policy.place_params(params)

    def note_group_bound(self, group: str, total: int) -> None:
        """Execute-while-scaling bookkeeping (ISSUE 17): one weight group
        of a streaming restore has been bound. The engine itself binds a
        complete tree via :meth:`bind_params`; THIS records which groups
        have arrived so the pressure heartbeat reports per-group
        readiness and the router can admit matching requests before the
        final group lands."""
        sg = self._scaleout_groups
        sg["total"] = max(int(total), sg["total"])
        if group and group not in sg["bound"]:
            sg["bound"].append(group)

    def precompile(self) -> dict:
        """AOT-compile every steady-state serving graph from SHAPES alone.

        XLA needs param shapes/dtypes, not values — so serving bring-up can
        run this concurrently with weight streaming (``self.params`` may be
        a ``jax.ShapeDtypeStruct`` tree from :func:`abstract_params`)
        instead of serializing a multi-second compile behind the weight
        load. Each ``.lower(...).compile()`` executable replaces the jitted
        function under the same cache key the serve loop resolves, so after
        ``bind_params`` the warmup/serve path dispatches straight into the
        compiled graph; with ``JAX_COMPILATION_CACHE_DIR`` set (every tpu9
        container) the executables land in the persistent cache too.
        Scalar positions are lowered with concrete ints — the weak-typed
        aval the serve loop's python-int arguments produce. The AOT logic
        itself lives with the graphs (``GraphFactory.precompile``); on a
        mesh policy the lowered specs carry the shardings, so the
        executables are the exact SPMD programs the serve loop runs."""
        return self.graphs.precompile(
            self.params, self.kv_cache,
            self._pool_dict() if self.paged else {},
            self._scratch if self.paged else {},
            self._mb if self.paged else 0,
            self._buckets, self._spec_lens, self._rng)

    def warmup(self) -> dict:
        """Precompile every prefill bucket and decode-window graph.

        Production engines pay XLA compiles at boot, not on the first user
        request: an 8B decode graph takes ~10 s to compile, and a window
        size that first occurs mid-traffic (e.g. K=1 when retirements
        stagger) would stall the whole decode batch behind a compile. Runs
        each graph once with all-inactive lanes (state is threaded back, so
        this is a no-op for correctness) and fences with a device→host copy.
        """
        import time as _time
        timings: dict[str, float] = {}
        if self.paged:
            # paged prefill path: chunk + splice + gather graphs
            t0 = _time.perf_counter()
            toks = jnp.zeros((1, self._chunk), jnp.int32)
            last, scratch = self._chunk_fn()(
                self.params, toks, 0, self._scratch, 0)
            self._scratch = scratch
            np.asarray(jax.device_get(last[:4]))
            timings[f"chunk_{self._chunk}_s"] = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            bs = self.ecfg.kv_block_size
            phys = jnp.full((self._chunk // bs,), self._trash_block,
                            jnp.int32)
            self._set_pool(self._splice_fn()(
                self._pool_dict(), self._scratch["k"], self._scratch["v"],
                0, phys))
            dense = self._gather_fn()(self._pool_dict(),
                                      self.kv_cache["table"][0])
            np.asarray(jax.device_get(dense["k"].ravel()[:4]))
            timings["splice_gather_s"] = _time.perf_counter() - t0
            g = max(1, self.ecfg.admit_group_chunks)
            if g > 1:
                # fused admission graph for the steady-state group size.
                # Partial tails never need their own scan shape:
                # _admit_paged drops to the warmed single-chunk graphs
                # for them, so this IS the last reachable signature
                # (graphcheck GRA005 / the recompile sentinel both
                # assert the set is closed here)
                t0 = _time.perf_counter()
                s = self.ecfg.max_seq_len
                offs = np.minimum(np.arange(g) * self._chunk,
                                  s - self._chunk).astype(np.int32)
                pool, self._scratch, last = self._chunk_group_fn(g)(
                    self.params, self._pool_dict(), self._scratch,
                    jnp.zeros((g, self._chunk), jnp.int32),
                    jnp.asarray(offs),
                    jnp.full((g,), self._chunk - 1, jnp.int32),
                    jnp.full((g, self._chunk // bs), self._trash_block,
                             jnp.int32))
                self._set_pool(pool)
                np.asarray(jax.device_get(last[:4]))
                timings[f"chunk_group_{g}_s"] = _time.perf_counter() - t0
        else:
            for bucket in self._buckets:
                t0 = _time.perf_counter()
                tokens = jnp.zeros((1, bucket), jnp.int32)
                last, cache = self._prefill_fn(bucket)(self.params,
                                                       tokens, 1)
                np.asarray(jax.device_get(last[:4]))
                timings[f"prefill_{bucket}_s"] = _time.perf_counter() - t0
                # the dense splice too (ISSUE 11): warmup previously left
                # it to compile on the FIRST admission — a post-seal
                # cache miss the recompile sentinel now counts as a
                # mid-serve stall. State threads back (slot 0's lanes get
                # the zero-prompt prefix; cache_len stays 0, so nothing
                # ever attends it).
                t0 = _time.perf_counter()
                self.kv_cache["k"], self.kv_cache["v"] = \
                    self._dense_splice_fn(bucket)(
                        self.kv_cache["k"], self.kv_cache["v"],
                        cache["k"], cache["v"], 0)
                timings[f"dsplice_{bucket}_s"] = _time.perf_counter() - t0
        inactive = jnp.zeros((self.ecfg.max_batch,), bool)
        for k in self.ecfg.decode_steps:
            t0 = _time.perf_counter()
            (self.last_token, self.kv_cache, self.cache_len, self._rng,
             toks) = self._decode_k(k)(
                self.params, self.kv_cache, self.last_token,
                self.cache_len, inactive, self._rng)
            np.asarray(jax.device_get(toks[-1, :4]))
            timings[f"decode_k{k}_s"] = _time.perf_counter() - t0
        for s in self._spec_lens:
            # speculative verify graphs: a spec window that first occurs
            # mid-traffic must not stall the batch behind an XLA compile
            t0 = _time.perf_counter()
            drafts = jnp.zeros((self.ecfg.max_batch, s), jnp.int32)
            (self.last_token, self.kv_cache, self.cache_len, self._rng,
             out, _n) = self._verify_fn(s)(
                self.params, self.kv_cache, self.last_token, drafts,
                self.cache_len, inactive, self._rng)
            np.asarray(jax.device_get(out[:4, 0]))
            timings[f"verify_s{s}_s"] = _time.perf_counter() - t0
        # recompile sentinel (ISSUE 11): warmup traced every steady-state
        # graph; from here a cache miss is a mid-serve compile incident
        self.graphs.seal()
        return timings

    async def stop(self) -> None:
        if self._profile_active:
            # a dangling device trace outlives the engine otherwise
            self._profile_remaining = 0
            self._deferred_windows.clear()
            self._profile_maybe_stop()
        if self._loop_task:
            # reap: absorbs the loop's CancelledError AND an Exception exit
            # (the loop ALREADY died; its failure was logged + fanned out)
            # but re-raises if stop() itself is cancelled (ASY003)
            await reap(self._loop_task, absorb_errors=True)
            self._loop_task = None
        # a clean shutdown must not strand callers: anything still
        # admitted/waiting/queued gets a terminal answer (the loop's
        # failure handler only covers Exception, not CancelledError)
        self._fail_all_requests("engine stopped")

    def cancel_request(self, req: "_Request") -> None:
        """Abandon a request (client disconnected mid-stream): the serve
        loop retires its slot at the next host sync instead of decoding
        the full budget into a queue nobody reads."""
        req.cancelled = True
        if req.done.is_set():
            return
        if req in self._wait_room:
            self._wait_room.remove(req)
            if req.queue is not None:
                req.queue.put_nowait(None)
            req.done.set()

    def active_stream_requests(self) -> list:
        """Live streaming requests (queue-backed, not cancelled) — what a
        graceful drain walks to migrate in-flight generations (ISSUE 16).
        The runner pushes dict events (``kv_key`` announcements) straight
        into these queues; the SSE relay forwards them verbatim."""
        return [req for slot, req in enumerate(self.slot_req)
                if req is not None and self.active[slot]
                and req.queue is not None and not req.cancelled]

    async def generate(self, prompt: list[int], max_new_tokens: int = 32,
                       request_id: str = "", stream: bool = False,
                       trace: Optional[tuple] = None,
                       budget_s: Optional[float] = None):
        """``trace`` is an optional remote span context ``(trace_id,
        parent_span_id)`` — set by the llm runner from the gateway's
        X-Tpu9-Trace header — under which the engine records its
        request/prefill/decode-window spans. None (the default) records
        no spans; latency metrics and the flight recorder are always on.

        ``budget_s`` (ISSUE 15) is the request's remaining deadline
        budget in seconds: a request still queued past it is never
        prefilled, and a slot still decoding past it is retired at the
        next window boundary (its KV blocks return to the pool
        immediately). None disables the deadline."""
        if self._dead_reason is not None:
            raise RuntimeError(
                f"engine is dead: {self._dead_reason} (restart the "
                "container — requests would hang forever)")
        if budget_s is not None and budget_s <= 0:
            raise TimeoutError(f"{DEADLINE_ERROR}: budget exhausted "
                               "before admission")
        # chunked prefill (paged mode) has no bucket cap — only the cache
        limit = self.ecfg.max_seq_len - 1 if self.paged else \
            min(self._buckets[-1], self.ecfg.max_seq_len - 1)
        if len(prompt) > limit:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine limit {limit}")
        if not prompt:
            raise ValueError("empty prompt")
        req = _Request(request_id=request_id or f"r{time.monotonic_ns()}",
                       prompt=list(prompt), max_new_tokens=max_new_tokens,
                       queue=asyncio.Queue() if stream else None,
                       trace=trace if trace and trace[0] else None,
                       t_enqueue_mono=time.monotonic(),
                       t_enqueue_wall=time.time(),
                       deadline_mono=(time.monotonic() + budget_s
                                      if budget_s else 0.0))
        await self._queue.put(req)
        self._stats["queued"] = self._queue.qsize()
        if stream:
            return req  # caller iterates req.queue
        await req.done.wait()
        if req.error:
            if req.error.startswith(DEADLINE_ERROR):
                raise TimeoutError(req.error)
            if req.error.startswith("engine"):
                # infrastructure failure (serve loop died / engine
                # stopped), not a request-shape problem: the runner maps
                # this to 500 and the gateway's failover retries it
                raise RuntimeError(req.error)
            raise ValueError(req.error)
        return req.generated

    # -- kvwire export / adopt (ISSUE 16) ------------------------------------
    # Synchronous by design: these run on the event loop between awaits,
    # so slot/allocator/prefix-cache state cannot shift underneath them
    # (the same atomicity the serve loop itself relies on). The device
    # reads inside block the loop for the gather duration — acceptable
    # for rare control-plane operations (handoff, drain, failover), and
    # XLA orders them after any in-flight window on the same arrays.

    def export_prefix_kv(self, tokens: list[int]) -> Optional[bytes]:
        """Serialize the longest prefix-cached block run covering
        ``tokens`` into a kvwire payload (None = nothing cached). The
        entry stays PINNED across the gather so a concurrent admission's
        eviction cannot recycle a block mid-device_get."""
        if not self.paged or self.ecfg.prefix_cache_blocks <= 0:
            return None
        entry = self.prefix_cache.acquire_for_export(list(tokens))
        if entry is None:
            self._stats["kvwire_export_misses"] += 1
            return None
        t0 = time.perf_counter()
        try:
            payload = self.pool.export_blocks(
                self.kv_cache, entry.blocks, entry.key, entry.n_tokens)
        finally:
            self.prefix_cache.release_pin(entry)
        self.metrics.observe("tpu9_kvwire_export_s",
                             time.perf_counter() - t0)
        self._stats["kvwire_exports"] += 1
        self._stats["kvwire_blocks_exported"] += len(entry.blocks)
        self._stats["kvwire_bytes_exported"] += len(payload)
        return payload

    def export_request_kv(self, request_id: str) -> Optional[bytes]:
        """Serialize an IN-FLIGHT request's full-block KV prefix (prompt
        + generated so far) — the drain-migration export. The slot's own
        block refs keep the blocks alive for the synchronous gather; the
        in-flight decode window only ever writes positions past the
        delivered sequence, which land in blocks beyond the shipped run.
        None = request not active or under one full block."""
        if not self.paged:
            return None
        from .paged_kv import PrefixCache
        for slot in range(self.ecfg.max_batch):
            req = self.slot_req[slot]
            if req is None or not self.active[slot] \
                    or req.request_id != request_id:
                continue
            seq = req.prompt + req.generated
            bs = self.ecfg.kv_block_size
            nb = min(len(seq) // bs, len(self._slot_blocks[slot]))
            if nb <= 0:
                return None
            t0 = time.perf_counter()
            payload = self.pool.export_blocks(
                self.kv_cache, self._slot_blocks[slot][:nb],
                PrefixCache._key(seq[:nb * bs]), nb * bs)
            self.metrics.observe("tpu9_kvwire_export_s",
                                 time.perf_counter() - t0)
            self._stats["kvwire_exports"] += 1
            self._stats["kvwire_blocks_exported"] += nb
            self._stats["kvwire_bytes_exported"] += len(payload)
            return payload
        return None

    def adopt_kv(self, payload: bytes) -> bool:
        """Splice a kvwire payload into fresh pool blocks and adopt the
        prefix into the cache, so the next ``generate`` over those tokens
        admits through the ordinary prefix-reuse path (chunked suffix
        prefill from the shipped watermark). False = could not adopt
        (pool pressure / prefix budget) — the caller falls back to plain
        re-prefill. Malformed payloads raise :class:`KvWireError` before
        any pool mutation."""
        if not self.paged or self.ecfg.prefix_cache_blocks <= 0:
            self._stats["kvwire_import_fallbacks"] += 1
            return False
        t0 = time.perf_counter()
        try:
            kv, adopted, header = self.pool.import_blocks(
                self.kv_cache, payload)
        except RuntimeError:
            # pool exhausted mid-splice: not an error, just no room —
            # re-prefill serves the request from scratch
            self._stats["kvwire_import_fallbacks"] += 1
            return False
        self.kv_cache = kv
        if not adopted:
            self._stats["kvwire_import_fallbacks"] += 1
            return False
        self.metrics.observe("tpu9_kvwire_import_s",
                             time.perf_counter() - t0)
        self._stats["kvwire_import_hits"] += 1
        self._stats["kvwire_blocks_imported"] += int(
            header.get("n_blocks", 0))
        self._stats["kvwire_bytes_imported"] += len(payload)
        return True

    def note_kvwire_ship(self, seconds: float) -> None:
        """Transport-side ship latency (cache put/get round-trip), fed by
        the runner — the engine itself never touches the transport."""
        self.metrics.observe("tpu9_kvwire_ship_s", seconds)

    def note_kvwire_fallback(self) -> None:
        """A ship that never reached import (fetch failed / fault
        injected): counted so hit-vs-fallback covers the whole path."""
        self._stats["kvwire_import_fallbacks"] += 1

    def flight_records(self, limit: int = 256,
                       since_seq: int = 0) -> list[dict]:
        """Flight-recorder tail (newest last); [] when disabled. The
        runner's /flight RPC and bench read through here so neither needs
        to know whether the recorder is on."""
        if self.flight is None:
            return []
        return self.flight.snapshot(limit=limit, since_seq=since_seq)

    def blackbox(self, reason: str, exception: str = "") -> dict:
        """Raw forensic material for a post-mortem record (ISSUE 14):
        scalar stats, scheduler + KV-pool state, HBM breakdown, the
        flight-recorder tail and the engine's recent spans. Plain host
        reads only — safe to call from a failure handler or next to a
        wedged serve loop. The runner wraps this through
        ``tpu9.observability.health.build_postmortem`` (the size bound)
        before shipping; the engine itself never imports the health
        module, keeping the observability leaf reverse-edge-free."""
        stats = self.stats()
        scheduler = {
            "active_slots": [int(i) for i in range(self.ecfg.max_batch)
                             if self.active[i]],
            "slot_requests": {
                str(i): req.request_id
                for i, req in enumerate(self.slot_req) if req is not None},
            "slot_generated": {
                str(i): len(req.generated)
                for i, req in enumerate(self.slot_req) if req is not None},
            "queued": self._queue.qsize(),
            "wait_room": len(self._wait_room),
            "admitting": (self._admitting.request_id
                          if self._admitting else ""),
            "inflight_steps": self._inflight_steps,
            "deferred_windows": len(self._deferred_windows),
            "pick_reason": self._pick_reason,
        }
        kv_pool = {}
        if self.paged:
            kv_pool = {"n_blocks": self.allocator.n_blocks,
                       "block_size": self.allocator.block_s,
                       "used": self.allocator.used_count,
                       "free": self.allocator.free_count,
                       "reserved": self.allocator.reserved,
                       "lifetime_allocs": self.pool.kv_allocs,
                       "kv_quant": self.ecfg.kv_quant if self.kv_quant
                       else ""}
            if self.prefix_cache is not None:
                kv_pool["prefix_cache"] = self.prefix_cache.stats()
        hbm = {k: stats.get(k, 0.0)
               for k in ("hbm_used_gb_per_chip", "hbm_peak_gb_per_chip",
                         "hbm_predicted_gb_per_chip",
                         "hbm_limit_gb_per_chip")}
        return {
            "reason": reason,
            "exception": exception,
            "stats": {k: v for k, v in stats.items()
                      if isinstance(v, (int, float, str, bool))},
            "scheduler": scheduler,
            "kv_pool": kv_pool,
            "hbm": hbm,
            "flight": self.flight_records(limit=64),
            "spans": tracer.export(limit=128),
        }

    def stats(self) -> dict:
        out = dict(self._stats)
        if not self.paged or not self.pool.tiered:
            # untiered stats surface is byte-identical to pre-tiering:
            # no kvtier_ family for the heartbeat/directory to chew on
            for k in [k for k in out if k.startswith("kvtier_")]:
                del out[k]
        out["active_streams"] = int(self.active.sum())
        out["queued"] = self._queue.qsize()
        out["engine_dead"] = self._dead_reason is not None
        # host mirror, NOT device_get: a blocking read here would stall
        # the event loop (health checks, SSE) behind the in-flight decode
        # window
        out["token_pressure"] = float(
            self._host_len.sum()
            / (self.ecfg.max_batch * self.ecfg.max_seq_len))
        # recompile sentinel (ISSUE 11): executable-cache misses. A
        # non-zero post_warmup count after warmup/precompile means a
        # serve-loop dispatch stalled every stream behind an XLA compile
        # — the runtime face of graphcheck's closed-signature invariant
        # (the factory also logs each incident loudly).
        out["graph_compiles"] = self.graphs.compiles
        out["graph_compiles_post_warmup"] = self.graphs.post_seal_compiles
        # cumulative seconds serving stalled behind those compiles — the
        # goodput accountant's recompile_stall bucket (ISSUE 12)
        out["graph_compile_stall_s"] = round(
            self.graphs.post_seal_stall_s, 6)
        # ---- fleet timeline series (ISSUE 12) ----
        # tokens/sec over the retained read-path window: each stats()
        # call (heartbeat cadence) appends the cumulative counter and
        # rates the delta — no serve-loop instrumentation at all
        now_m = time.monotonic()
        self._tps_window.append((now_m, self._stats["tokens_generated"]))
        while (len(self._tps_window) > 2
               and now_m - self._tps_window[0][0] > 30.0):
            self._tps_window.pop(0)
        t0, c0 = self._tps_window[0]
        span = now_m - t0
        out["tokens_per_sec"] = round(
            (self._stats["tokens_generated"] - c0) / span, 3) \
            if span > 0.5 else 0.0
        # decode physics constants + device kind: the gateway prices
        # MFU/MBU timeline series from these (benchsuite.physics specs
        # stay control-plane-side; the engine ships raw arithmetic)
        out["decode_bytes_per_token_per_chip"] = \
            self._phys_bytes_per_token_per_chip
        out["decode_flops_per_token_per_chip"] = \
            self._phys_flops_per_token_per_chip
        out["device_kind"] = self._device_kind
        # topology (ISSUE 9): flat scalars so the runner heartbeat can
        # forward them into the store hash behind /api/v1/metrics
        # "engines" unchanged — tp/fsdp/n_chips plus live per-chip HBM
        # (max across the submesh; 0.0 where the backend has no memory
        # stats, i.e. CPU). A 1x1 engine reports tp=1 so the fleet view
        # can tell "single chip" from "not reporting".
        topo = self.policy.describe()
        out["topo_tp"] = topo["tp"]
        out["topo_fsdp"] = topo["fsdp"]
        out["topo_n_chips"] = topo["n_chips"]
        out["hbm_used_gb_per_chip"] = self.policy.hbm_used_gb_per_chip()
        # ---- replica health plane (ISSUE 14) ----
        # liveness watermark: progress counters + dispatch/progress ages
        # the runner-side watchdog classifies ok/degraded/stalled from.
        # Ages are computed here (one clock) so the watchdog never has to
        # correlate monotonic clocks across the RPC boundary.
        out["windows_processed"] = self._windows_processed
        out["last_dispatch_age_s"] = (
            round(now_m - self._last_dispatch_mono, 3)
            if self._last_dispatch_mono else -1.0)
        out["last_progress_age_s"] = round(
            now_m - self._last_progress_mono, 3)
        # HBM watermarks: peak tracks the read-path samples (heartbeat
        # cadence); predicted is the planner-arithmetic residency of the
        # exact trees this engine holds; limit is the chip's capacity
        # (0.0 where the backend has no memory stats, i.e. CPU)
        self._hbm_peak_gb = max(self._hbm_peak_gb,
                                out["hbm_used_gb_per_chip"])
        out["hbm_peak_gb_per_chip"] = self._hbm_peak_gb
        out["hbm_predicted_gb_per_chip"] = self.hbm_predicted_gb_per_chip
        out["hbm_limit_gb_per_chip"] = self._hbm_limit_gb
        # speculative-decoding acceptance (ISSUE 5): proposed/accepted are
        # cumulative; the rate is the fleet-comparable signal the runner
        # heartbeats and the router aggregates
        out["spec_enabled"] = bool(self._spec_lens)
        prop = self._stats["spec_proposed"]
        out["spec_acceptance_rate"] = (
            self._stats["spec_accepted"] / prop if prop else 0.0)
        # flight recorder + profiling hook + latency decomposition
        # (ISSUE 8). "latency" is flat p50/p95/count scalars per phase so
        # the runner heartbeat can forward them into the store hash that
        # backs /api/v1/metrics "engines" unchanged.
        if self.flight is not None:
            out["flight"] = self.flight.summary()
        out["profile"] = {"armed": self._profile_remaining,
                          "active": self._profile_active,
                          "path": self._profile_path,
                          "error": self._profile_error}
        # cold-start decomposition (ISSUE 13): flat coldstart_* scalars so
        # the runner heartbeat forwards them into the pressure hash that
        # backs /api/v1/metrics "engines" and /api/v1/coldstart unchanged
        for k, v in self.bringup.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"coldstart_{k}"] = v
        # execute-while-scaling readiness (ISSUE 17): flat scaleout_*
        # scalars, same heartbeat-forwarding contract as coldstart_*.
        # No partial bring-up in flight (total == 0) reports fully ready
        # so steady-state replicas are indistinguishable from before.
        sg = self._scaleout_groups
        out["scaleout_groups_total"] = sg["total"]
        out["scaleout_groups_ready"] = len(sg["bound"])
        out["scaleout_ready_frac"] = round(
            len(sg["bound"]) / sg["total"], 4) if sg["total"] else 1.0
        out["scaleout_ready_groups"] = ",".join(sg["bound"])
        lat = {}
        summaries = self.metrics.to_dict()["summaries"]
        for phase in ("ttft", "tbt", "queue_wait", "prefill",
                      "decode_window", "e2e"):
            snap = summaries.get(f"tpu9_engine_{phase}_s")
            if snap:
                lat[f"{phase}_p50_s"] = round(snap["p50"], 6)
                lat[f"{phase}_p95_s"] = round(snap["p95"], 6)
                lat[f"{phase}_count"] = snap["count"]
                lat[f"{phase}_mean_s"] = round(snap["mean"], 6)
        out["latency"] = lat
        # kvwire (ISSUE 16): ship-path latency percentiles, flat under
        # the same kvwire_* prefix as the counters so the runner
        # heartbeat forwards the whole family with one startswith loop.
        # "export"/"import" are engine-side gather/splice; "ship" is the
        # transport round-trip the runner observes via note_kvwire_ship.
        for op in ("export", "import", "ship"):
            snap = summaries.get(f"tpu9_kvwire_{op}_s")
            if snap:
                out[f"kvwire_{op}_p50_s"] = round(snap["p50"], 6)
                out[f"kvwire_{op}_p95_s"] = round(snap["p95"], 6)
        # kv tiering (ISSUE 20): occupancy + paging latency percentiles,
        # flat under kvtier_* — the same one-startswith-loop heartbeat
        # contract as kvwire_*. Only emitted when a host tier exists, so
        # the untiered heartbeat is byte-identical to before.
        if self.paged and self.pool.tiered:
            ts = self.pool.tier_stats()
            out["kvtier_device_blocks"] = ts["device_blocks"]
            out["kvtier_device_bytes"] = ts["device_bytes"]
            out["kvtier_host_blocks"] = ts["host_blocks"]
            out["kvtier_host_bytes"] = ts["host_bytes"]
            out["kvtier_host_entries"] = ts["host_entries"]
            out["kvtier_host_evictions"] = ts["host_evictions"]
            out["kvtier_peer_spills"] = self.pool.peer_spills
            out["kvtier_hits_device"] = self.prefix_cache.hits_device
            out["kvtier_hits_host"] = self.prefix_cache.hits_host
            for op in ("downpage", "uppage"):
                snap = summaries.get(f"tpu9_kvtier_{op}_s")
                if snap:
                    out[f"kvtier_{op}_p50_s"] = round(snap["p50"], 6)
                    out[f"kvtier_{op}_p95_s"] = round(snap["p95"], 6)
        if self.paged:
            out["kv_blocks_used"] = self.allocator.used_count
            out["kv_blocks_free"] = self.allocator.free_count
            out["kv_blocks_reserved"] = self.allocator.reserved
            # the fleet router divides free tokens (blocks × size) into
            # an in-flight admission budget — see tpu9.router.admission
            out["kv_block_size"] = self.allocator.block_s
            # int8 pool (ISSUE 6): the free/used counts above already
            # reflect the ~2x equal-HBM pool, so the router's admission
            # math needs no change — this is observability. The MODE
            # string ("" = off), not a bool: a fleet mixing future modes
            # must be able to tell which pool format a replica runs
            out["kv_quant"] = self.ecfg.kv_quant if self.kv_quant else ""
            out["queued"] += len(self._wait_room)
            out["prefix_cache"] = self.prefix_cache.stats()
            # admission pressure for the router: reserved fraction is the
            # honest "can I take another request" signal under paging
            out["token_pressure"] = max(
                out["token_pressure"],
                self.allocator.reserved / max(self.allocator.n_blocks, 1))
        return out

    # -- engine loop ---------------------------------------------------------

    async def _admit_paged(self, req: _Request, slot: int):
        """Paged admission: reserve budget, reuse any cached prefix blocks,
        chunk-prefill the suffix in FUSED GROUPS of ``admit_group_chunks``
        (one lax.scan dispatch per group, splice included — VERDICT r04
        #6), interleaving a decode window between groups so the running
        batch keeps producing tokens during a long admission. Zero host
        syncs here; the serve loop syncs the whole admission batch once.
        Returns the first-token device value."""
        from .paged_kv import blocks_for
        bs = self.ecfg.kv_block_size
        n = len(req.prompt)
        if self._slot_blocks[slot]:
            # leftovers (bench_reset_slots / defensive): return them first
            self.allocator.release(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
        self._slot_reserved[slot] = self.allocator.reserve(
            self._worst_case_tokens(req))

        entry = self.prefix_cache.lookup(req.prompt) \
            if self.ecfg.prefix_cache_blocks > 0 else None
        if entry is not None and entry.tier == "host":
            # host-tier hit (ISSUE 20): re-place the planes through the
            # sharding policy before the blocks can be shared. Degrades
            # to a plain miss (full recompute) if the host copy raced a
            # reap — never errors.
            entry = await self._uppage_entry(entry, req.request_id)
        shared: list[int] = list(entry.blocks) if entry else []
        p = entry.n_tokens if entry else 0
        # cached prefixes land on BLOCK boundaries, chunk windows on CHUNK
        # boundaries; an unaligned p would put the final window past
        # max_seq_len where dynamic_update_slice clamps its start backwards
        # over valid prefix KV (advisor r04). Round p down to a chunk
        # multiple: positions [p', p) are recomputed and re-spliced with
        # bit-identical values (KV at position t depends only on tokens
        # <= t, which the cached prefix shares), so overwriting the shared
        # blocks is value-safe.
        p -= p % self._chunk
        self.allocator.retain(shared)
        if entry is not None:
            # blocks are retained: a concurrent admission's eviction can
            # no longer free them under us — drop the lookup pin
            self.prefix_cache.release_pin(entry)

        total_blocks = blocks_for(n + 1, bs)
        fresh = self._alloc_blocks(total_blocks - len(shared))
        self._slot_blocks[slot] = shared + fresh
        # the DEVICE table row stays all-trash until admission completes:
        # decode windows interleaved below scatter every INACTIVE lane's
        # write through its table row at position 0, which must never be
        # one of the blocks being spliced here
        row = np.full((self._mb,), self._trash_block, dtype=np.int32)
        row[:len(self._slot_blocks[slot])] = self._slot_blocks[slot]

        scratch = self._scratch
        if p:
            dense = self._gather_fn()(self._pool_dict(), jnp.asarray(row))
            scratch = {"k": dense["k"], "v": dense["v"]}
            self._stats["admit_dispatches"] += 1

        # per-chunk host arrays, built once (the former per-chunk python
        # bookkeeping between dispatches was the loop's biggest host-side
        # overhead — now it's one numpy pass + one transfer per group)
        c = self._chunk
        nb = c // bs
        suffix = req.prompt[p:]
        m = len(suffix)
        n_chunks = -(-m // c)
        req.admit_cached = p
        req.admit_chunks = n_chunks
        toks_all = np.zeros((n_chunks, c), dtype=np.int32)
        offsets = np.zeros((n_chunks,), dtype=np.int32)
        last_idxs = np.zeros((n_chunks,), dtype=np.int32)
        # chunk tail past the slot's blocks = padded garbage → write it to
        # the dedicated trash block, never a real one
        phys_all = np.full((n_chunks, nb), self._trash_block,
                           dtype=np.int32)
        for k_chunk, i in enumerate(range(0, m, c)):
            valid = min(c, m - i)
            toks_all[k_chunk, :valid] = suffix[i:i + valid]
            offsets[k_chunk] = p + i
            last_idxs[k_chunk] = valid - 1
            first_block = (p + i) // bs
            for j in range(nb):
                idx = first_block + j
                if idx < len(self._slot_blocks[slot]):
                    phys_all[k_chunk, j] = self._slot_blocks[slot][idx]

        last = None
        group = max(1, self.ecfg.admit_group_chunks)
        k_chunk = 0
        while k_chunk < n_chunks:
            # FULL groups use the fused scan graph warmup compiled; a
            # partial tail (2..group-1 chunks) runs through the warmed
            # single-chunk graphs instead of JIT-compiling a fresh scan
            # shape mid-traffic (which would stall every active stream
            # behind an XLA compile)
            g = group if n_chunks - k_chunk >= group else 1
            sl = slice(k_chunk, k_chunk + g)
            if g > 1:
                pool, scratch, last = self._chunk_group_fn(g)(
                    self.params, self._pool_dict(), scratch,
                    jnp.asarray(toks_all[sl]),
                    jnp.asarray(offsets[sl]), jnp.asarray(last_idxs[sl]),
                    jnp.asarray(phys_all[sl]))
                self._set_pool(pool)
                self._stats["admit_dispatches"] += 1
            else:
                last, scratch = self._chunk_fn()(
                    self.params, jnp.asarray(toks_all[sl]),
                    int(offsets[k_chunk]), scratch, int(last_idxs[k_chunk]))
                self._set_pool(self._splice_fn()(
                    self._pool_dict(), scratch["k"], scratch["v"],
                    int(offsets[k_chunk]), jnp.asarray(phys_all[k_chunk])))
                self._stats["admit_dispatches"] += 2
            k_chunk += g
            if k_chunk < n_chunks:
                # long admission: keep the decode batch producing tokens
                # and let streaming consumers drain
                self._interleave_decode_window()
                await asyncio.sleep(0)
        self._scratch = scratch

        if self.ecfg.prefix_cache_blocks > 0:
            self.prefix_cache.insert(req.prompt, self._slot_blocks[slot])

        self._push_table(slot)            # real row becomes visible NOW
        self.cache_len = self.cache_len.at[slot].set(n)
        self._host_len[slot] = n
        self._rng, sub = jax.random.split(self._rng)
        first = sample_logits(last, sub, temperature=self.ecfg.temperature,
                              top_k=self.ecfg.top_k, top_p=self.ecfg.top_p)
        self.last_token = self.last_token.at[slot, 0].set(first)
        self._occupy_slot(req, slot)
        return first

    # -- KV tiering: up-page / down-page (ISSUE 20) --------------------------

    async def _uppage_entry(self, entry, request_id: str = ""):
        """Re-place a host-tier prefix hit into fresh pool blocks through
        the sharding policy. The entry arrives PINNED from ``lookup`` and
        the pin holds for the whole up-page, so eviction pressure (a
        concurrent admission's ``evict_for_space``) can never reap it
        mid-copy. Returns the entry, device-resident and still pinned —
        or None (pin released) when the host copy was lost to a reap:
        the caller degrades to a plain recompute, never an error.

        Concurrent admissions hitting the same host entry await the
        first up-page instead of double-filling blocks."""
        cache = self.prefix_cache
        key = entry.key
        fut = self._uppage_inflight.get(key)
        if fut is not None:
            cache.release_pin(entry)
            await fut
            ent = cache._entries.get(key)
            if ent is None or ent.tier != "device":
                return None                 # primary failed: recompute
            ent.pins += 1                   # re-pin for our admission
            cache.pinned += 1
            return ent
        fut = asyncio.get_running_loop().create_future()
        self._uppage_inflight[key] = fut
        t0 = time.perf_counter()
        try:
            planes = self.pool.uppage_planes(entry)
            if planes is None:
                # the host copy vanished between advertisement and use
                # (the stale-directory window): recompute, never error
                self._stats["kvtier_uppage_failures"] += 1
                self.pool.kv_decisions.append(
                    {"decision": "recompute", "request_id": request_id,
                     "chosen": "recompute",
                     "rejected": [{"alternative": f"host:{key.hex()[:16]}",
                                   "reason": "host_copy_lost"}],
                     "signals": {"n_tokens": entry.n_tokens}})
                cache.release_pin(entry)
                if entry.pins == 0:
                    cache.drop(key, kind="evict")
                return None
            try:
                self._set_pool(self.pool.complete_uppage(
                    self._pool_dict(), entry, planes))
            except RuntimeError:
                # pool exhausted mid-up-page: the prefix stays on the
                # host tier for a calmer window; this admission simply
                # recomputes — pressure must never error a request
                self._stats["kvtier_uppage_failures"] += 1
                self.pool.kv_decisions.append(
                    {"decision": "recompute", "request_id": request_id,
                     "chosen": "recompute",
                     "rejected": [{"alternative": f"host:{key.hex()[:16]}",
                                   "reason": "pool_exhausted"}],
                     "signals": {"n_tokens": entry.n_tokens}})
                cache.release_pin(entry)
                return None
            # the scatter is dispatched, not synced: yield so the serve
            # loop can run while it lands — admission's own data deps
            # guarantee residency before the blocks are read
            await asyncio.sleep(0)
            dt = time.perf_counter() - t0
            self._stats["kvtier_uppages"] += 1
            self.metrics.observe("tpu9_kvtier_uppage_s", dt)
            self.pool.kv_decisions.append(
                {"decision": "pull", "request_id": request_id,
                 "chosen": f"host:{key.hex()[:16]}",
                 "signals": {"n_tokens": entry.n_tokens,
                             "uppage_s": round(dt, 6)}})
            return entry
        except Exception:
            cache.release_pin(entry)
            raise
        finally:
            self._uppage_inflight.pop(key, None)
            if not fut.done():
                fut.set_result(True)

    def _kvtier_tick(self) -> None:
        """Window-boundary down-paging: when the scheduler's low-water
        check fires, LRU unpinned prefix entries spill to host DRAM
        *before* allocation pressure lets ``_evict_one`` destroy them.
        Runs only at the window boundary — the gather is a device sync
        and must never ride the per-token path."""
        quota = self.scheduler.downpage_quota()
        if not quota:
            return
        for entry in self.prefix_cache.spill_candidates(quota):
            key_hex = entry.key.hex()[:16]
            n_tok = entry.n_tokens
            t0 = time.perf_counter()
            if not self.pool.downpage(self._pool_dict(), entry):
                continue
            dt = time.perf_counter() - t0
            self._stats["kvtier_downpages"] += 1
            self.metrics.observe("tpu9_kvtier_downpage_s", dt)
            self.pool.kv_decisions.append(
                {"decision": "spill", "chosen": f"host:{key_hex}",
                 "signals": {"n_tokens": n_tok,
                             "free_blocks": self.allocator.free_count,
                             "downpage_s": round(dt, 6)}})

    # -- KV tiering: runner-facing surface (ISSUE 20) ------------------------
    # Event-loop-synchronous like the kvwire methods: pure host state.

    def kvtier_digest(self, top_k: int = 48) -> str:
        """Bounded top-K prefix-key summary for the directory heartbeat:
        ``hex16:tier:n_tokens`` comma-joined, MRU first — never the full
        key list."""
        if self.prefix_cache is None:
            return ""
        ents = sorted(self.prefix_cache._entries.values(),
                      key=lambda e: -e.last_used)[:top_k]
        return ",".join(
            f"{e.key.hex()[:16]}:{'h' if e.tier == 'host' else 'd'}"
            f":{e.n_tokens}" for e in ents)

    def kvtier_deltas(self, since: int) -> tuple:
        """Tier-change journal after cursor ``since`` (evictions/spills
        the directory must retract) + the new cursor. The runner advances
        its cursor only once a heartbeat is accepted."""
        if self.prefix_cache is None:
            return [], 0
        return self.prefix_cache.deltas_since(since)

    def drain_kv_spills(self) -> list:
        """Queued peer-cache spill payloads ``(key_hex16, payload,
        n_tokens)`` — the runner owns the transport."""
        if self.pool is None:
            return []
        return self.pool.drain_peer_spills()

    def drain_kvtier_decisions(self) -> list:
        """Journaled ``kv_tier`` decision dicts (spill/pull/recompute/
        evict choices made inside the serving plane). The runner records
        them into the decision ledger — the one-way evidence flow BND001
        pins (serving must not import the ledger). Destructive read."""
        if self.pool is None or not self.pool.kv_decisions:
            return []
        out = list(self.pool.kv_decisions)
        self.pool.kv_decisions.clear()
        return out

    # -- observability hooks (ISSUE 8) ---------------------------------------
    # All host-side bookkeeping on state the loop already holds: monotonic
    # durations, per-engine metric observes (per request / per window,
    # never per token), and — only for requests carrying a remote trace
    # context — span records into the process tracer ring the runner ships
    # on its pressure heartbeat.

    def _obs_admit_start(self, req: _Request, t0_mono: float,
                         t0_wall: float) -> None:
        wait = max(t0_mono - req.t_enqueue_mono, 0.0)
        self.metrics.observe("tpu9_engine_queue_wait_s", wait)
        if req.trace is None:
            return
        trace_id, parent = req.trace
        topo = self.policy.describe()
        req.span = tracer.start_span(
            "engine.request", trace_id=trace_id, parent_id=parent,
            attrs={"request_id": req.request_id,
                   "prompt_tokens": len(req.prompt),
                   "max_new_tokens": req.max_new_tokens,
                   # multichip evidence rides the PR-8 observability
                   # layer (ISSUE 9): which submesh served this request
                   "tp": topo["tp"], "n_chips": topo["n_chips"]})
        req.span_id = req.span.span_id
        # backdate to the enqueue anchor: the request span covers
        # queue-wait + prefill + every decode window
        req.span.start, req.span.start_mono = (req.t_enqueue_wall,
                                               req.t_enqueue_mono)
        tracer.record_span(
            "engine.queue_wait", trace_id, req.span.span_id,
            req.t_enqueue_wall, req.t_enqueue_mono,
            attrs={"request_id": req.request_id}, end_mono=t0_mono)

    def _obs_admit_end(self, req: _Request, t0_mono: float, t0_wall: float,
                       il0: int) -> None:
        dur = max(time.monotonic() - t0_mono, 0.0)
        self._last_progress_mono = time.monotonic()   # admission = progress
        self.metrics.observe("tpu9_engine_prefill_s", dur)
        interleaved = self._stats["admit_interleaved_windows"] - il0
        if req.trace is not None and req.span is not None:
            tracer.record_span(
                "engine.prefill", req.trace[0], req.span.span_id,
                t0_wall, t0_mono,
                attrs={"request_id": req.request_id,
                       "prompt_tokens": len(req.prompt),
                       "cached_tokens": req.admit_cached,
                       "chunks": req.admit_chunks,
                       "interleaved_windows": interleaved})
        if self.flight is not None:
            self.flight.record(
                "admit", request_id=req.request_id, slot=req.slot,
                prompt_tokens=len(req.prompt),
                cached_tokens=req.admit_cached, chunks=req.admit_chunks,
                interleaved=interleaved, dur_s=round(dur, 6))

    def _obs_stamp_window(self, win: _Window) -> _Window:
        win.t_mono = time.monotonic()
        win.t_wall = time.time()
        # liveness watermark (ISSUE 14): the watchdog's "did the loop
        # still reach a dispatch" stamp
        self._last_dispatch_mono = win.t_mono
        win.pick = self._pick_reason
        if self.paged:
            win.kv_snap = (self.allocator.used_count,
                           self.allocator.free_count,
                           self.allocator.reserved)
        return win

    def _obs_window(self, win: _Window, t_host0: float) -> None:
        """One flight record + per-traced-request window spans at host
        processing time. ``wait_s`` (dispatch → fan-out start) includes
        the deliberate one-window overlap; ``host_s`` is the fan-out."""
        now_m = time.monotonic()
        self.metrics.observe("tpu9_engine_decode_window_s",
                             max(t_host0 - win.t_mono, 0.0))
        # liveness watermark (ISSUE 14): a host-processed window IS
        # progress — the counter the watchdog requires to keep moving
        # while work is queued
        self._windows_processed += 1
        self._last_progress_mono = now_m
        delivered = win.delivered or {}
        if self.flight is not None:
            slots = {s: r.request_id
                     for s, r in enumerate(win.reqs)
                     if r is not None and win.mask[s]}
            rec = {"k": win.k, "pick": win.pick,
                   "batch": int(win.mask.sum()),
                   "slots": slots, "tokens": delivered,
                   "wait_s": round(max(t_host0 - win.t_mono, 0.0), 6),
                   "host_s": round(max(now_m - t_host0, 0.0), 6)}
            topo = self.policy.describe()
            if topo["n_chips"] > 1:
                # stamp the submesh onto multichip window records only —
                # 1x1 flight records stay byte-identical to the pre-split
                # engine's
                rec.update(tp=topo["tp"], n_chips=topo["n_chips"])
            if win.kind == "verify":
                prop, acc = win.spec_stats or (0, 0)
                rec.update(spec_proposed=prop, spec_accepted=acc,
                           spec_rollback=prop - acc,
                           spec_len=win.spec_len)
            if win.kv_snap:
                used, free, reserved = win.kv_snap
                rec.update(kv_used=used, kv_free=free, kv_reserved=reserved,
                           kv_alloc=self.pool.kv_allocs
                           - self._flight_kv_allocs)
                self._flight_kv_allocs = self.pool.kv_allocs
                if self.prefix_cache is not None:
                    ev = self.prefix_cache.evictions
                    rec.update(
                        prefix_evictions=ev - self._flight_evictions,
                        prefix_pinned=self.prefix_cache.pinned)
                    self._flight_evictions = ev
            self.flight.record(win.kind, **rec)
        for slot, n_tok in delivered.items():
            req = win.reqs[slot]
            if (n_tok > 0 and req is not None and req.trace is not None
                    and req.span_id):
                tracer.record_span(
                    "engine.decode_window", req.trace[0], req.span_id,
                    win.t_wall, win.t_mono,
                    attrs={"kind": win.kind, "k": win.k, "tokens": n_tok,
                           "pick": win.pick})

    def _obs_first_token(self, req: _Request) -> None:
        req.t_first_mono = time.monotonic()
        self.metrics.observe(
            "tpu9_engine_ttft_s",
            max(req.t_first_mono - req.t_enqueue_mono, 0.0))

    def _obs_done(self, req: _Request) -> None:
        """Idempotent: reachable from both _retire (slot completion) and
        _finish (error/cancel paths) — only the FIRST call observes."""
        now = time.monotonic()
        n = len(req.generated)
        if req.t_enqueue_mono:
            self.metrics.observe("tpu9_engine_e2e_s",
                                 max(now - req.t_enqueue_mono, 0.0))
            if req.t_first_mono and n > 1:
                self.metrics.observe(
                    "tpu9_engine_tbt_s",
                    max(now - req.t_first_mono, 0.0) / (n - 1))
            req.t_enqueue_mono = 0.0
        if req.span is not None:
            sp, req.span = req.span, None     # exactly one finish per span
            sp.attrs["tokens_generated"] = n
            tracer.finish_span(sp, status="error" if req.error else "ok")

    # -- on-demand profiling (ISSUE 8) ---------------------------------------

    def arm_profile(self, windows: int = 8, out_dir: str = "") -> dict:
        """Arm ``jax.profiler`` for the next ``windows`` dispatched
        windows. Returns the dump path immediately; the trace starts at
        the next window boundary and stops once the armed windows have
        drained — a live replica gets profiled without a restart or a
        single out-of-band device sync."""
        if windows <= 0:
            raise ValueError(f"windows must be positive, got {windows}")
        if self._profile_active or self._profile_remaining > 0:
            return {"path": self._profile_path,
                    "windows": self._profile_remaining,
                    "already_armed": True}
        import tempfile
        self._profile_path = out_dir or tempfile.mkdtemp(
            prefix="tpu9-profile-")
        self._profile_remaining = windows
        self._profile_error = ""
        if self.flight is not None:
            self.flight.record("profile", event="armed",
                               windows=windows, path=self._profile_path)
        return {"path": self._profile_path, "windows": windows}

    def _profile_window_start(self) -> None:
        if self._profile_remaining <= 0 or self._profile_active:
            return
        try:
            jax.profiler.start_trace(self._profile_path)
            self._profile_active = True
        except Exception as exc:    # noqa: BLE001 — profiling must never
            # take the serve loop down; surface the failure in stats()
            self._profile_error = f"{type(exc).__name__}: {exc}"
            self._profile_remaining = 0

    def _profile_window_dispatched(self) -> None:
        if self._profile_active and self._profile_remaining > 0:
            self._profile_remaining -= 1

    def _profile_maybe_stop(self, idle: bool = False) -> None:
        """Stop once every armed window has been host-processed (device
        work complete), so the dump covers the whole window set.
        ``idle=True`` (the serve loop about to park) stops EARLY even
        with armed windows left: traffic dried up before the armed count,
        and a partial dump beats tracing hours of parked silence — which
        would also leave ``arm_profile`` reporting already_armed forever."""
        if not self._profile_active or self._deferred_windows:
            return
        if self._profile_remaining > 0 and not idle:
            return
        left, self._profile_remaining = self._profile_remaining, 0
        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 — see start
            self._profile_error = f"{type(exc).__name__}: {exc}"
        self._profile_active = False
        if self.flight is not None:
            self.flight.record("profile", event="stopped",
                               path=self._profile_path,
                               windows_left=left,
                               error=self._profile_error)

    def _occupy_slot(self, req: _Request, slot: int) -> None:
        req.slot = slot
        self.active[slot] = True
        self.slot_req[slot] = req
        if self._spec_lens:
            from .spec import make_slot_state
            self._spec_slots[slot] = make_slot_state(req.prompt)

    def _interleave_decode_window(self) -> None:
        """Dispatch one decode window for the active batch WITHOUT syncing
        (results processed after the admission sync). Room accounting must
        include steps already in flight from earlier interleaved windows."""
        if not self.active.any():
            return
        ks = self.ecfg.decode_steps
        want = ks[1] if len(ks) > 1 else ks[0]
        # total in-flight overshoot must stay within the max(decode_steps)
        # +1 slack _worst_case_tokens reserved per slot — past that, block
        # growth could eat another slot's reservation
        slack = max(ks) - self._inflight_steps
        limit = min(want, slack)
        for slot in range(self.ecfg.max_batch):
            req = self.slot_req[slot]
            if req is None or not self.active[slot]:
                continue
            # budget is SOFT (same rationale as _pick_steps: overshoot
            # tokens are discarded host-side at retire, and one nearly-
            # done stream must not stall interleaving for all the others);
            # cache room is HARD
            remaining = (req.max_new_tokens - len(req.generated)
                         - self._inflight_steps)
            room = (self.ecfg.max_seq_len - 1 - int(self._host_len[slot])
                    - self._inflight_steps)
            limit = min(limit, max(1, remaining), max(0, room))
        k = 0
        for cand in ks:
            if cand <= limit:
                k = max(k, cand)
        if k <= 0:
            return              # out of cache room or reservation slack
        for slot in range(self.ecfg.max_batch):
            if self.active[slot]:
                self._ensure_slot_blocks(
                    slot, min(int(self._host_len[slot])
                              + self._inflight_steps + k + 1,
                              self.ecfg.max_seq_len))
        (self.last_token, self.kv_cache, self.cache_len, self._rng,
         toks) = self._decode_k(k)(
            self.params, self.kv_cache, self.last_token, self.cache_len,
            jnp.asarray(self.active), self._rng)
        self._pick_reason = "interleave"
        self._deferred_windows.append(self._obs_stamp_window(
            _Window(kind="decode", k=k, toks=toks, mask=self.active.copy(),
                    reqs=tuple(self.slot_req))))
        self._inflight_steps += k
        self._stats["decode_steps"] += k
        self._stats["admit_interleaved_windows"] += 1

    async def _admit(self, req: _Request, slot: int):
        """Prefill + cache splice for one request. Returns the slot's
        first-token DEVICE value — the serve loop syncs a whole admission
        batch in one host round-trip (each blocking ``int()`` here would
        cost a full RTT, brutal over a TPU relay)."""
        t0_mono, t0_wall = time.monotonic(), time.time()
        self._obs_admit_start(req, t0_mono, t0_wall)
        il0 = self._stats["admit_interleaved_windows"]
        if self.paged:
            first = await self._admit_paged(req, slot)
        else:
            first = self._admit_dense(req, slot)
        self._obs_admit_end(req, t0_mono, t0_wall, il0)
        return first

    def _admit_dense(self, req: _Request, slot: int):
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, :n] = req.prompt[:bucket]
        last, cache = self._prefill_fn(bucket)(
            self.params, jnp.asarray(tokens), n)
        # copy prefix cache into the slot's lanes — jitted + donated: the
        # eager form copied the whole [L,B,S,KH,D] cache twice per
        # admission (GBs of HBM traffic + a transient second allocation)
        self.kv_cache["k"], self.kv_cache["v"] = self._dense_splice_fn(
            bucket)(self.kv_cache["k"], self.kv_cache["v"],
                    cache["k"], cache["v"], slot)
        self.cache_len = self.cache_len.at[slot].set(n)
        self._host_len[slot] = n
        # sample the first generated token from the prefill logits
        self._rng, sub = jax.random.split(self._rng)
        first = sample_logits(last, sub, temperature=self.ecfg.temperature,
                              top_k=self.ecfg.top_k, top_p=self.ecfg.top_p)
        self.last_token = self.last_token.at[slot, 0].set(first)
        self._occupy_slot(req, slot)
        return first

    def _deliver_first(self, req: _Request, first: int) -> None:
        req.generated.append(first)
        self._obs_first_token(req)
        st = self._spec_slots[req.slot] if req.slot >= 0 else None
        if st is not None:
            st.proposer.append(first)
        if req.queue is not None:
            req.queue.put_nowait(first)
        # the prefill-sampled token may already satisfy the stop conditions
        if (req.max_new_tokens <= 1
                or (self.ecfg.eos_id >= 0 and first == self.ecfg.eos_id)):
            self._retire(req.slot)

    def _retire(self, slot: int) -> None:
        req = self.slot_req[slot]
        self.active[slot] = False
        self.slot_req[slot] = None
        self._spec_slots[slot] = None
        self.cache_len = self.cache_len.at[slot].set(0)
        self._host_len[slot] = 0
        if self.paged:
            # physical blocks back to the pool (prefix-cache refs keep
            # shared prefix blocks alive), worst-case reservation released
            self.kv_cache["table"] = self.pool.release_slot(slot)
        if req is not None:
            self._obs_done(req)
            if req.queue is not None:
                req.queue.put_nowait(None)
            req.done.set()

    def _room_for(self, req: _Request) -> bool:
        """Paged admission control: a request enters only when the pool can
        reserve its worst case — so mid-decode allocation can never fail."""
        return (not self.paged
                or self.allocator.can_reserve(self._worst_case_tokens(req)))

    @staticmethod
    def _req_expired(req: "_Request") -> bool:
        return (req.deadline_mono > 0
                and time.monotonic() > req.deadline_mono)

    def _expire_unadmitted(self, req: "_Request") -> None:
        """Deadline expiry BEFORE prefill (ISSUE 15): the whole point of
        admission-side deadlines — chips never prefill an answer the
        client has already stopped waiting for."""
        self._stats["deadline_expired"] += 1
        self._finish(req, error=f"{DEADLINE_ERROR}: budget exhausted "
                                "before prefill")

    def _next_admittable(self) -> Optional[_Request]:
        while self.paged and self._wait_room:
            head = self._wait_room[0]
            if head.cancelled or self._req_expired(head):
                self._wait_room.pop(0)
                if head.cancelled:
                    self._finish(head)
                else:
                    self._expire_unadmitted(head)
                continue
            if self._room_for(head):
                return self._wait_room.pop(0)
            return None                     # FIFO: don't starve the head
        while not self._queue.empty():
            req = self._queue.get_nowait()
            if req.cancelled:
                self._finish(req)
                continue
            if self._req_expired(req):
                self._expire_unadmitted(req)
                continue
            if self._room_for(req):
                return req
            self._wait_room.append(req)
            return None
        return None

    def _finish(self, req: _Request, error: str = "") -> None:
        if error and not req.error:
            req.error = error
        self._obs_done(req)
        if req.queue is not None:
            req.queue.put_nowait(None)
        req.done.set()

    def _fail_all_requests(self, reason: str) -> None:
        """Give every known request a terminal answer: admitted slots, the
        one mid-admission, the wait room, and the queue. A caller left
        awaiting a dead engine hangs forever."""
        for req in ([r for r in self.slot_req if r is not None]
                    + ([self._admitting] if self._admitting else [])
                    + list(self._wait_room)):
            self._finish(req, error=reason)
        self._wait_room.clear()
        self._admitting = None
        while not self._queue.empty():
            self._finish(self._queue.get_nowait(), error=reason)

    async def _serve_loop(self) -> None:
        try:
            await self._serve_loop_inner()
        except asyncio.CancelledError:
            raise
        except Exception as exc:      # noqa: BLE001
            # a dead loop must not leave callers awaiting forever — fail
            # every known request with the cause, and make generate()
            # fail FAST from now on (the loop is never restarted; the
            # runner's health surface flips on engine_dead)
            import logging
            logging.getLogger("tpu9.serving").exception("engine loop died")
            self._dead_reason = f"{type(exc).__name__}: {exc}"
            # black box FIRST (ISSUE 14): _fail_all_requests clears the
            # scheduler state the record exists to capture. A crashing
            # snapshot must never mask the original failure.
            try:
                self.last_postmortem = self.blackbox(
                    "engine_crash", f"{type(exc).__name__}: {exc}")
            except Exception:   # noqa: BLE001 — evidence is best-effort
                logging.getLogger("tpu9.serving").exception(
                    "post-mortem snapshot failed")
            self._fail_all_requests(f"engine failure: {exc}")
            raise

    async def _serve_loop_inner(self) -> None:
        while True:
            # armed profile done? stop once every profiled window drained
            self._profile_maybe_stop()
            # admit as many queued requests as there are free slots; ALL
            # their first tokens sync in one device round-trip at the end.
            # An imminent admission first drains the steady-state overlap
            # window: its steps occupy the reservation slack the
            # admission-interleaved decode windows need, and its
            # retirements may free the very slot being admitted into.
            if self._deferred_windows and self._admission_can_proceed():
                self._drain_windows()
            pending: list[tuple[_Request, Any]] = []
            while not self.active.all():
                req = self._next_admittable()
                if req is None:
                    break
                slot = int(np.argmin(self.active))
                self._admitting = req       # failure fan-out must see it
                pending.append((req, await self._admit(req, slot)))
                self._admitting = None

            if not self.active.any() and not pending:
                if self.paged and self._wait_room:
                    # engine idle with a waiting head means reservations
                    # are zero, so the ONLY way it can't admit is being
                    # bigger than the whole pool — fail it loudly (prefix-
                    # cache pressure is handled inside _alloc_blocks)
                    head = self._wait_room.pop(0)
                    head.error = "request exceeds KV pool capacity"
                    if head.queue is not None:
                        head.queue.put_nowait(None)   # release SSE readers
                    head.done.set()
                    continue
                if self._deferred_windows:
                    # a zombie overlap window (its slots all retired during
                    # the previous iteration's drain, with this successor
                    # already in flight): process it BEFORE parking, or its
                    # device work goes unaccounted and the armed profiler
                    # below can never observe an empty flight
                    self._drain_windows()
                # an armed profile must stop NOW — even mid-arm-count —
                # parked-idle time must not leak into the dump
                self._profile_maybe_stop(idle=True)
                # idle: block for work
                req = await self._queue.get()
                if req.cancelled:
                    self._finish(req)
                    continue
                if self._req_expired(req):
                    self._expire_unadmitted(req)
                    continue
                if not self._room_for(req):
                    self._wait_room.append(req)
                    continue
                self._admitting = req
                pending.append((req, await self._admit(req, 0)))
                self._admitting = None

            if pending:
                # tpu9: noqa[JAX001] intended sync point: ONE batched read of all admitted prefill first-tokens (TTFT requires delivering them now)
                firsts = np.asarray(jax.device_get(
                    jnp.stack([f for _, f in pending])))
                for (req, _), first in zip(pending, firsts):
                    self._deliver_first(req, int(first))
                # windows dispatched during those admissions: their tokens
                # are ready by now (device work ordered before firsts) —
                # drain them in one transfer
                self._drain_windows()

            if not self.active.any():
                # retirements can only land at host processing: leftover
                # in-flight windows must drain before the idle block
                if self._deferred_windows:
                    self._drain_windows()
                continue

            # window boundary: down-page LRU prefixes to host DRAM when
            # the pool nears eviction pressure (ISSUE 20; no-op untiered)
            if self.paged and self.pool.tiered:
                self._kvtier_tick()
            # one WINDOW for the whole batch — speculative verify when the
            # acceptance EWMAs justify it, classic k-step decode otherwise
            self._profile_window_start()
            win = self._dispatch_window()
            if win is not None:
                self._profile_window_dispatched()
                self._deferred_windows.append(win)
                # steady-state overlap (ISSUE 5 satellite): keep exactly
                # ONE window in flight — the host fan-out of every older
                # window runs WHILE the new one computes on device,
                # instead of serializing host work behind each sync
                while len(self._deferred_windows) > 1:
                    self._process_deferred(self._deferred_windows.pop(0))
            # yield to the event loop so new requests can land
            await asyncio.sleep(0)

    # -- window dispatch / processing ---------------------------------------

    def _dispatch_window(self) -> Optional[_Window]:
        s = self._spec_room_len()
        if s > 0:
            s = self._spec_gate(s)
        if s > 0:
            # drafts must continue the DELIVERED history: drain any
            # in-flight window first so the proposers' view matches the
            # device last_token (classic windows keep the overlap; a
            # verify window instead amortizes the sync over up to 1+s
            # tokens per slot)
            while self._deferred_windows:
                self._process_deferred(self._deferred_windows.pop(0))
            if not self.active.any():
                return None
            from .spec import build_drafts
            drafts, n_real = build_drafts(self._spec_slots, self.active, s)
            if int(n_real.sum()) > 0:
                return self._dispatch_verify(s, drafts, n_real)
            # nothing to propose anywhere: a verify pass would be a pure
            # waste — fall through to a classic window
        k = self._pick_steps()
        if self.paged:
            # lazy physical growth: each active slot gets blocks for this
            # window's writes (covered by its reservation). Clamp to
            # max_seq_len: _pick_steps already bounds in-window positions
            # to the cache, and a near-full slot must not demand a 17th
            # block of a 16-wide table.
            for slot in range(self.ecfg.max_batch):
                if self.active[slot]:
                    self._ensure_slot_blocks(
                        slot, min(int(self._host_len[slot])
                                  + self._inflight_steps + k + 1,
                                  self.ecfg.max_seq_len))
        (self.last_token, self.kv_cache,
         self.cache_len, self._rng, toks) = self._decode_k(k)(
            self.params, self.kv_cache, self.last_token,
            self.cache_len, jnp.asarray(self.active), self._rng)
        self._stats["decode_steps"] += k
        self._inflight_steps += k
        return self._obs_stamp_window(
            _Window(kind="decode", k=k, toks=toks,
                    mask=self.active.copy(), reqs=tuple(self.slot_req)))

    def _dispatch_verify(self, s: int, drafts, n_real) -> _Window:
        t = s + 1
        if self.paged:
            for slot in range(self.ecfg.max_batch):
                if self.active[slot]:
                    self._ensure_slot_blocks(
                        slot, min(int(self._host_len[slot]) + t + 1,
                                  self.ecfg.max_seq_len))
        (self.last_token, self.kv_cache, self.cache_len, self._rng, out,
         n_acc) = self._verify_fn(s)(
            self.params, self.kv_cache, self.last_token,
            jnp.asarray(drafts), self.cache_len, jnp.asarray(self.active),
            self._rng)
        self._stats["spec_windows"] += 1
        self._inflight_steps += t
        self._pick_reason = "spec"
        return self._obs_stamp_window(
            _Window(kind="verify", k=t, toks=out, n_acc=n_acc,
                    mask=self.active.copy(), reqs=tuple(self.slot_req),
                    spec_len=s, n_real=n_real))

    def _drain_windows(self) -> None:
        """Host-process every in-flight window. ONE transfer for all of
        them — N sequential device_gets would pay N round-trips over a
        TPU relay."""
        wins, self._deferred_windows = self._deferred_windows, []
        if not wins:
            return
        # tpu9: noqa[JAX001] intended sync point: the ONE batched window-boundary device_get (PR 5); N sequential reads would pay N round-trips
        payload = jax.device_get(
            [(w.toks,) if w.n_acc is None else (w.toks, w.n_acc)
             for w in wins])
        for w, arrs in zip(wins, payload):
            self._inflight_steps -= w.k
            self._process_window_host(
                w, np.asarray(arrs[0]),  # tpu9: noqa[JAX001] arrs are already host memory (device_get above); asarray is a no-copy view
                np.asarray(arrs[1]) if len(arrs) > 1 else None)  # tpu9: noqa[JAX001] host memory, no device sync

    def _process_deferred(self, win: _Window) -> None:
        if win.n_acc is None:
            # tpu9: noqa[JAX001] intended sync point: the window's compute is DONE (one-window-overlap drains here); this read is the host fan-out
            toks, n_acc = jax.device_get(win.toks), None
        else:
            toks, n_acc = jax.device_get((win.toks, win.n_acc))  # tpu9: noqa[JAX001] intended sync point: batched toks+n_acc read at the window boundary
            n_acc = np.asarray(n_acc)  # tpu9: noqa[JAX001] host memory after device_get, no sync
        self._inflight_steps -= win.k
        self._process_window_host(win, np.asarray(toks), n_acc)  # tpu9: noqa[JAX001] host memory after device_get, no sync

    def _deliver_token(self, slot: int, tok: int) -> None:
        """Deliver ONE generated token to the slot's request, retiring the
        slot when it satisfies a stop condition (budget / EOS / cache
        room)."""
        req = self.slot_req[slot]
        req.generated.append(tok)
        self._host_len[slot] += 1
        self._stats["tokens_generated"] += 1
        st = self._spec_slots[slot]
        if st is not None:
            st.proposer.append(tok)
        if req.queue is not None:
            req.queue.put_nowait(tok)
        hit_eos = self.ecfg.eos_id >= 0 and tok == self.ecfg.eos_id
        # prompt + generated must fit the cache
        out_of_room = self._host_len[slot] >= self.ecfg.max_seq_len - 1
        if (len(req.generated) >= req.max_new_tokens or hit_eos
                or out_of_room):
            # remaining window tokens for this slot are noise (the device
            # kept going); retire discards them by flipping active off
            self._retire(slot)

    def _slot_live(self, win: _Window, slot: int) -> bool:
        """A window's tokens belong to a slot only if the request that
        occupied it AT DISPATCH is still there — identity, not just
        activity: with a window in flight a slot can retire AND be
        re-admitted before its tokens are processed, and the old window's
        tokens must never leak into the new request's stream."""
        return (bool(win.mask[slot]) and bool(self.active[slot])
                and self.slot_req[slot] is win.reqs[slot])

    def _process_window_host(self, win: _Window, window,
                             n_acc=None) -> None:
        """Host-side consumption of one window's tokens. Decode windows
        carry [k, B] (every step, every slot); verify windows carry the
        model outputs [B, 1+s] plus per-slot accepted-draft counts —
        tokens-per-slot-per-window is VARIABLE (1..1+s)."""
        t_host0 = time.monotonic()
        win.delivered = {}
        if win.kind == "verify":
            self._process_verify_host(win, window, n_acc)
        else:
            self._process_decode_host(win, window)
        self._obs_window(win, t_host0)

    def _process_decode_host(self, win: _Window, window) -> None:
        shadow: dict[int, list[int]] = {}
        if self._spec_lens:
            # shadow drafts: what WOULD prompt lookup have proposed for
            # this window? Proposed HERE — at processing time, before any
            # of the window's tokens are appended — the proposer history
            # is exactly the pre-window state, so the drafts align with
            # the tokens they are graded against (proposing at DISPATCH
            # would be one in-flight window stale under the steady-state
            # overlap and misalign by k mod cycle-period). The window's
            # real tokens grade them below: a free, always-fresh
            # acceptance estimate that opens the verify gate the moment a
            # stream turns repetitive, with no blind probe windows.
            m = min(win.k, self._spec_lens[-1])
            for slot in range(self.ecfg.max_batch):
                st = self._spec_slots[slot]
                if st is not None and self._slot_live(win, slot):
                    shadow[slot] = st.proposer.propose(m)
        delivered: list[list[int]] = [[] for _ in range(self.ecfg.max_batch)]
        for step in range(win.k):
            for slot in range(self.ecfg.max_batch):
                if not self._slot_live(win, slot):
                    continue
                if self.slot_req[slot].cancelled:
                    # client gone mid-stream: stop decoding into a queue
                    # nobody reads and free the slot for live work
                    self._retire(slot)
                    continue
                if self._req_expired(self.slot_req[slot]):
                    # deadline passed mid-generation: retire NOW — the
                    # slot's KV blocks return to the pool this window,
                    # not after the remaining budget decodes into a
                    # response nobody is waiting for
                    self._stats["deadline_expired"] += 1
                    self.slot_req[slot].error = \
                        f"{DEADLINE_ERROR}: budget exhausted mid-decode"
                    self._retire(slot)
                    continue
                tok = int(window[step, slot])
                delivered[slot].append(tok)
                self._deliver_token(slot, tok)
        for slot, sh in shadow.items():
            m = min(len(sh), len(delivered[slot]))
            if m == 0:
                continue
            acc = 0
            while acc < m and sh[acc] == delivered[slot][acc]:
                acc += 1
            st = self._spec_slots[slot]
            if st is not None:
                st.observe(m, acc)
        win.delivered = {slot: len(toks)
                         for slot, toks in enumerate(delivered) if toks}

    def _process_verify_host(self, win: _Window, out, n_acc) -> None:
        s = win.spec_len
        win_proposed = win_accepted = 0
        for slot in range(self.ecfg.max_batch):
            if not self._slot_live(win, slot):
                continue
            acc = int(n_acc[slot])
            st = self._spec_slots[slot]
            n_real = int(win.n_real[slot])
            if n_real > 0:
                win_proposed += n_real
                win_accepted += min(acc, n_real)
            if st is not None and n_real > 0:
                # EWMA and counters see only what this slot actually
                # proposed — zero-padded lanes (and any padded TAIL of a
                # partial proposal) must not drag acceptance down for
                # drafts that were never offered. Padding accepted by
                # chance is capped off the accounting too; its tokens are
                # still delivered (they are the model's own outputs).
                st.observe(n_real, min(acc, n_real))
                self._stats["spec_proposed"] += n_real
                self._stats["spec_accepted"] += min(acc, n_real)
            if self.slot_req[slot].cancelled:
                self._retire(slot)
                continue
            if self._req_expired(self.slot_req[slot]):
                self._stats["deadline_expired"] += 1
                self.slot_req[slot].error = \
                    f"{DEADLINE_ERROR}: budget exhausted mid-decode"
                self._retire(slot)
                continue
            req = self.slot_req[slot]
            n_delivered = 0
            for i in range(acc + 1):
                self._deliver_token(slot, int(out[slot, i]))
                n_delivered += 1
                if self.slot_req[slot] is not req:
                    break          # EOS / budget / room hit inside the run
            if n_delivered:
                win.delivered[slot] = n_delivered
        win.spec_stats = (win_proposed, win_accepted)
