"""LLM inference engine: continuous batching over jitted prefill/decode.

TPU-first rationale: the engine compiles exactly two graphs per shape bucket —
``prefill(tokens[1, Tpad])`` and ``decode(tokens[B,1])`` — and keeps the KV
cache as a persistent on-device buffer donated through every decode step, so
steady-state decoding is one fused XLA computation per token across the whole
batch with zero host↔device traffic except the sampled ids.

Slots: fixed max_batch decode lanes. New requests prefill (bucketed lengths to
bound compile count), then join the decode batch at their slot index. This is
the same admission shape the reference's LLM-aware pod router assumes
(``pkg/abstractions/pod/llm.go`` token-pressure/active-streams), which the
gateway reads from the engine's ``stats()``.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (DecoderConfig, decoder_forward,
                                  init_kv_cache)
from ..ops.sampling import sample_logits

Params = dict[str, Any]


@dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8
    max_seq_len: int = 2048
    prefill_buckets: tuple = (128, 512, 2048)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1              # -1 disables EOS stopping


@dataclass
class _Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    done: asyncio.Event = field(default_factory=asyncio.Event)
    queue: Optional[asyncio.Queue] = None   # set for streaming requests


class InferenceEngine:
    """Continuous-batching engine around a decoder model."""

    def __init__(self, params: Params, cfg: DecoderConfig,
                 engine_cfg: EngineConfig = EngineConfig()):
        self.params = params
        self.cfg = cfg
        self.ecfg = engine_cfg
        b, s = engine_cfg.max_batch, engine_cfg.max_seq_len
        self.kv_cache = init_kv_cache(cfg, b, s)
        self.cache_len = jnp.zeros((b,), jnp.int32)     # valid prefix per slot
        self.active = np.zeros((b,), dtype=bool)
        self.slot_req: list[Optional[_Request]] = [None] * b
        self.last_token = jnp.zeros((b, 1), jnp.int32)
        self._rng = jax.random.PRNGKey(0)
        self._queue: asyncio.Queue[_Request] = asyncio.Queue()
        self._loop_task: Optional[asyncio.Task] = None
        self._compiled: dict[int, Any] = {}
        self._decode_fn = self._build_decode()
        self._stats = {"active_streams": 0, "queued": 0, "tokens_generated": 0,
                       "decode_steps": 0}

    # -- compiled steps ------------------------------------------------------

    def _build_decode(self):
        cfg, ecfg = self.cfg, self.ecfg

        def decode(params, kv_cache, last_token, cache_len, active, rng):
            positions = cache_len[:, None]              # next position per slot
            logits, kv_cache = decoder_forward(
                params, last_token, cfg, positions=positions,
                kv_cache=kv_cache, cache_len=cache_len + 1, decode=True)
            rng, sub = jax.random.split(rng)
            next_tok = sample_logits(logits[:, -1], sub,
                                     temperature=ecfg.temperature,
                                     top_k=ecfg.top_k, top_p=ecfg.top_p)
            # only live slots advance; idle lanes stay parked at 0 so the
            # token-pressure signal reflects real cache occupancy
            new_len = cache_len + active.astype(jnp.int32)
            return next_tok[:, None].astype(jnp.int32), kv_cache, new_len, rng

        return jax.jit(decode, donate_argnums=(1,))

    def _prefill_fn(self, bucket: int):
        if bucket in self._compiled:
            return self._compiled[bucket]
        cfg = self.cfg

        def prefill(params, tokens, length):
            # tokens [1, bucket] padded; returns logits at the last real token
            # and the per-layer k/v for the prefix.
            logits, cache = decoder_forward(
                params, tokens, cfg,
                kv_cache=init_kv_cache(cfg, 1, bucket), decode=False)
            last = logits[0, length - 1]
            return last, cache

        fn = jax.jit(prefill)
        self._compiled[bucket] = fn
        return fn

    def _bucket_for(self, n: int) -> int:
        for b in self.ecfg.prefill_buckets:
            if n <= b:
                return b
        return self.ecfg.prefill_buckets[-1]

    # -- public API ----------------------------------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._serve_loop())

    async def stop(self) -> None:
        if self._loop_task:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None

    async def generate(self, prompt: list[int], max_new_tokens: int = 32,
                       request_id: str = "", stream: bool = False):
        limit = min(self.ecfg.prefill_buckets[-1], self.ecfg.max_seq_len - 1)
        if len(prompt) > limit:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine limit {limit}")
        if not prompt:
            raise ValueError("empty prompt")
        req = _Request(request_id=request_id or f"r{time.monotonic_ns()}",
                       prompt=list(prompt), max_new_tokens=max_new_tokens,
                       queue=asyncio.Queue() if stream else None)
        await self._queue.put(req)
        self._stats["queued"] = self._queue.qsize()
        if stream:
            return req  # caller iterates req.queue
        await req.done.wait()
        return req.generated

    def stats(self) -> dict:
        out = dict(self._stats)
        out["active_streams"] = int(self.active.sum())
        out["queued"] = self._queue.qsize()
        out["token_pressure"] = float(
            np.asarray(jax.device_get(self.cache_len)).sum()
            / (self.ecfg.max_batch * self.ecfg.max_seq_len))
        return out

    # -- engine loop ---------------------------------------------------------

    def _admit(self, req: _Request, slot: int) -> None:
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, :n] = req.prompt[:bucket]
        last, cache = self._prefill_fn(bucket)(
            self.params, jnp.asarray(tokens), n)
        # copy prefix cache into the slot's lanes
        k = self.kv_cache["k"]
        v = self.kv_cache["v"]
        k = jax.lax.dynamic_update_slice(
            k, cache["k"][:, :, :bucket], (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            v, cache["v"][:, :, :bucket], (0, slot, 0, 0, 0))
        self.kv_cache = {"k": k, "v": v}
        self.cache_len = self.cache_len.at[slot].set(n)
        # sample the first generated token from the prefill logits
        self._rng, sub = jax.random.split(self._rng)
        first = int(sample_logits(last, sub, temperature=self.ecfg.temperature,
                                  top_k=self.ecfg.top_k, top_p=self.ecfg.top_p))
        self.last_token = self.last_token.at[slot, 0].set(first)
        req.slot = slot
        req.generated.append(first)
        if req.queue is not None:
            req.queue.put_nowait(first)
        self.active[slot] = True
        self.slot_req[slot] = req
        # the prefill-sampled token may already satisfy the stop conditions
        if (req.max_new_tokens <= 1
                or (self.ecfg.eos_id >= 0 and first == self.ecfg.eos_id)):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self.slot_req[slot]
        self.active[slot] = False
        self.slot_req[slot] = None
        self.cache_len = self.cache_len.at[slot].set(0)
        if req is not None:
            if req.queue is not None:
                req.queue.put_nowait(None)
            req.done.set()

    async def _serve_loop(self) -> None:
        while True:
            # admit as many queued requests as there are free slots
            admitted = False
            while not self._queue.empty() and not self.active.all():
                req = self._queue.get_nowait()
                slot = int(np.argmin(self.active))
                self._admit(req, slot)
                admitted = True

            if not self.active.any():
                # idle: block for work
                req = await self._queue.get()
                slot = 0
                self._admit(req, slot)
                admitted = True

            if not self.active.any():
                continue

            # one decode step for the whole batch
            (self.last_token, self.kv_cache,
             self.cache_len, self._rng) = self._decode_fn(
                self.params, self.kv_cache, self.last_token,
                self.cache_len, jnp.asarray(self.active), self._rng)
            self._stats["decode_steps"] += 1

            tokens = np.asarray(jax.device_get(self.last_token))[:, 0]
            lens = np.asarray(jax.device_get(self.cache_len))
            for slot in range(self.ecfg.max_batch):
                if not self.active[slot]:
                    continue
                req = self.slot_req[slot]
                tok = int(tokens[slot])
                req.generated.append(tok)
                self._stats["tokens_generated"] += 1
                if req.queue is not None:
                    req.queue.put_nowait(tok)
                hit_eos = (self.ecfg.eos_id >= 0 and tok == self.ecfg.eos_id)
                # prompt + generated must fit the cache
                out_of_room = lens[slot] >= self.ecfg.max_seq_len - 1
                if (len(req.generated) >= req.max_new_tokens or hit_eos
                        or out_of_room):
                    self._retire(slot)
            # yield to the event loop so new requests can land
            await asyncio.sleep(0)
