"""Serving graph factory: every jitted/AOT-compiled XLA computation the
engine dispatches, in one module (ISSUE 9 engine split).

The engine split's graph-building third: prefill (bucketed dense +
chunked paged + fused admission groups), windowed decode, speculative
verify, and the pool splice/gather plumbing. The factory owns the
compiled-executable cache and is the ONLY place serving code traces jax —
the engine orchestrates admission/scheduling/fan-out around these
callables and never opens a ``jax.jit`` itself.

Sharding boundary: the factory is handed a :mod:`tpu9.serving.shard`
policy and pins every KV-state output with ``policy.constrain_kv`` before
returning it from a traced body — on a mesh that keeps the donated pool
head-sharded across every round trip; on the single-device policy the
hook is the identity, so a ``1x1`` engine traces exactly the graphs the
pre-split engine did (same cache keys, no constraint ops).

Dtype boundary: int8 KV quantize/dequant stays in ``ops.quant`` +
``models.transformer``; the factory only routes the scale planes through
the same physical indices as the payload (``traced_splice``).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Iterator

import jax
import jax.numpy as jnp

from ..models.transformer import decoder_forward, init_kv_cache
from ..ops.sampling import sample_logits

Params = dict[str, Any]

log = logging.getLogger("tpu9.serving")


class GraphFactory:
    """Builds + caches the engine's compiled graphs for one (model,
    engine-config, sharding-policy) triple. ``chunk`` is the validated
    chunked-prefill length (0 = dense mode); ``kv_quant`` whether the
    paged pool carries int8 payload + scale planes."""

    def __init__(self, cfg, ecfg, policy, chunk: int = 0,
                 kv_quant: bool = False):
        self.cfg = cfg
        self.ecfg = ecfg
        self.policy = policy
        self.chunk = chunk
        self.kv_quant = kv_quant
        self.compiled: dict[Any, Any] = {}
        # recompile sentinel (ISSUE 11): executable-cache misses. After
        # seal() (warmup/precompile done) a miss means steady-state
        # serving is about to stall every active stream behind an XLA
        # compile — the runtime face of graphcheck's closed-signature
        # pass, surfaced via engine.stats()["graph_compiles*"].
        self.compiles = 0
        self.post_seal_compiles = 0
        # cumulative seconds serving stalled behind post-seal compiles
        # (ISSUE 12: the goodput accountant's "recompile_stall" waste
        # bucket) — measured as the first dispatch's wall time, since
        # jax.jit compiles lazily at that first call
        self.post_seal_stall_s = 0.0
        self._sealed = False

    def _build(self, key, builder):
        """Cache-or-build a graph under ``key`` — the ONE miss path, so
        the sentinel can't be bypassed by a new getter."""
        fn = self.compiled.get(key)
        if fn is None:
            self.compiles += 1
            if self._sealed:
                self.post_seal_compiles += 1
                log.warning(
                    "post-warmup graph compile: key=%r — a steady-state "
                    "window is stalling behind an XLA compile; the "
                    "precompile signature set is open (graphcheck GRA005 "
                    "should have caught this)", key)
                fn = self.compiled[key] = self._timed_first_call(
                    key, builder())
                return fn
            fn = self.compiled[key] = builder()
        return fn

    def _timed_first_call(self, key, real):
        """Wrap a post-seal-built callable so its FIRST dispatch — the one
        that pays the XLA compile — is timed into ``post_seal_stall_s``,
        then unwrap (steady state dispatches the bare executable)."""
        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            out = real(*args, **kwargs)
            self.post_seal_stall_s += time.perf_counter() - t0
            self.compiled[key] = real
            return out
        return timed

    def seal(self) -> None:
        """Mark the executable cache complete: every signature the serve
        loop can request is compiled. Called by engine warmup/precompile;
        later misses are counted + logged as recompile incidents."""
        self._sealed = True

    # -- decode window -------------------------------------------------------

    def build_decode(self, k: int = 1):
        cfg, ecfg, policy = self.cfg, self.ecfg, self.policy

        def one_step(params, kv_cache, last_token, cache_len, active, rng):
            positions = cache_len[:, None]          # next position per slot
            logits, kv_cache = decoder_forward(
                params, last_token, cfg, positions=positions,
                kv_cache=kv_cache, cache_len=cache_len + 1, decode=True)
            rng, sub = jax.random.split(rng)
            next_tok = sample_logits(logits[:, -1], sub,
                                     temperature=ecfg.temperature,
                                     top_k=ecfg.top_k, top_p=ecfg.top_p)
            # only live slots advance; idle lanes stay parked at 0 so the
            # token-pressure signal reflects real cache occupancy
            new_len = cache_len + active.astype(jnp.int32)
            return next_tok[:, None].astype(jnp.int32), kv_cache, new_len, rng

        def decode(params, kv_cache, last_token, cache_len, active, rng):
            def body(carry, _):
                last, kv, clen, r = carry
                last, kv, clen, r = one_step(params, kv, last, clen,
                                             active, r)
                return (last, kv, clen, r), last[:, 0]

            (last, kv_cache, cache_len, rng), toks = jax.lax.scan(
                body, (last_token, kv_cache, cache_len, rng), None,
                length=k)
            # toks [k, B]: the host consumes the whole window in one sync
            return (last, policy.constrain_kv(kv_cache), cache_len, rng,
                    toks)

        return jax.jit(decode, donate_argnums=(1,))

    def decode_k(self, k: int):
        return self._build(("decode", k), lambda: self.build_decode(k))

    # -- speculative verify --------------------------------------------------

    def build_verify(self, s: int):
        """Jitted speculative-verify graph (ISSUE 5 tentpole): ONE batched
        forward over ``[B, 1+s]`` positions — column 0 is the device
        last_token, columns 1..s the host-proposed draft tokens. The model
        emits its OWN token at every position; a draft survives only while
        it equals the model's output, so the emitted stream is exactly
        what classic decode would have produced (greedy parity is
        bit-exact — drafts can only be cheap, never wrong). Per slot the
        graph returns the accepted-prefix length and the model's bonus
        token, and advances cache_len past accepted positions only —
        rejected draft positions keep garbage KV that attention masks out
        and the next window overwrites (paged re-splice / dense
        re-scatter)."""
        cfg, ecfg, policy = self.cfg, self.ecfg, self.policy
        t = s + 1

        def verify(params, kv_cache, last_token, drafts, cache_len,
                   active, rng):
            tokens = jnp.concatenate(
                [last_token, drafts.astype(jnp.int32)], axis=1)  # [B, t]
            positions = cache_len[:, None] + jnp.arange(t)[None, :]
            logits, kv_cache = decoder_forward(
                params, tokens, cfg, positions=positions,
                kv_cache=kv_cache, cache_len=cache_len + t, decode=False)
            rng, sub = jax.random.split(rng)
            out = sample_logits(logits, sub, temperature=ecfg.temperature,
                                top_k=ecfg.top_k,
                                top_p=ecfg.top_p).astype(jnp.int32)  # [B, t]
            # longest agreeing prefix of the drafts, per slot
            agree = (tokens[:, 1:] == out[:, :-1]).astype(jnp.int32)
            n_acc = jnp.cumprod(agree, axis=1).sum(axis=1)        # [B]
            # the model's own next token after the accepted run
            bonus = jnp.take_along_axis(out, n_acc[:, None], axis=1)
            new_len = cache_len + (n_acc + 1) * active.astype(jnp.int32)
            return (bonus, policy.constrain_kv(kv_cache), new_len, rng,
                    out, n_acc)

        return jax.jit(verify, donate_argnums=(1,))

    def verify_fn(self, s: int):
        return self._build(("verify", s), lambda: self.build_verify(s))

    # -- dense prefill -------------------------------------------------------

    def prefill_fn(self, bucket: int):
        cfg, policy = self.cfg, self.policy

        def build():
            def prefill(params, tokens, length):
                # tokens [1, bucket] padded; returns logits at the last
                # real token and the per-layer k/v for the prefix.
                logits, cache = decoder_forward(
                    params, tokens, cfg,
                    kv_cache=init_kv_cache(cfg, 1, bucket), decode=False)
                last = logits[0, length - 1]
                return last, policy.constrain_kv(cache)

            return jax.jit(prefill)

        return self._build(bucket, build)

    def dense_splice_fn(self, bucket: int):
        """Jitted, cache-donating copy of a prefill's [L,1,bucket,...] KV
        into one slot's lanes of the dense [L,B,S,...] cache."""
        policy = self.policy

        def build():
            def splice(k, v, ck, cv, slot):
                k = jax.lax.dynamic_update_slice(
                    k, ck[:, :, :bucket], (0, slot, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(
                    v, cv[:, :, :bucket], (0, slot, 0, 0, 0))
                out = policy.constrain_kv({"k": k, "v": v})
                return out["k"], out["v"]

            return jax.jit(splice, donate_argnums=(0, 1))

        return self._build(("dsplice", bucket), build)

    # -- paged chunked prefill -----------------------------------------------

    def traced_chunk_step(self, params, scratch, tok_row, offset,
                          last_idx):
        """Traced body shared by the single-chunk and fused-group graphs
        (one implementation — the two admission paths must never diverge):
        prefill one C-token chunk into the scratch at ``offset`` and
        return the logits at ``last_idx``."""
        c = self.chunk
        positions = offset + jnp.arange(c)[None, :]
        logits, scratch = decoder_forward(
            params, tok_row[None, :], self.cfg, positions=positions,
            kv_cache=scratch, cache_len=offset + c, decode=False)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], last_idx, axis=0, keepdims=False)
        return last, scratch

    def traced_splice(self, pool, scratch_k, scratch_v, offset, phys):
        """Traced block copy shared by the splice and fused-group graphs:
        scratch positions [offset, offset+C) → pool blocks phys[0..C/BS).
        An int8 pool quantizes each block on the way in (per-vector absmax
        scales land in the scale planes at the same physical index)."""
        bs = self.ecfg.kv_block_size
        pool = dict(pool)
        for j in range(self.chunk // bs):
            blk_k = jax.lax.dynamic_slice_in_dim(
                scratch_k[:, 0], offset + j * bs, bs, axis=1)
            blk_v = jax.lax.dynamic_slice_in_dim(
                scratch_v[:, 0], offset + j * bs, bs, axis=1)
            if "k_scale" in pool:
                from ..ops.quant import quantize_kv
                blk_k, sk = quantize_kv(blk_k)   # [L,bs,KH,D], [L,bs,KH]
                blk_v, sv = quantize_kv(blk_v)
                pool["k_scale"] = pool["k_scale"].at[:, phys[j]].set(sk)
                pool["v_scale"] = pool["v_scale"].at[:, phys[j]].set(sv)
            pool["k"] = pool["k"].at[:, phys[j]].set(blk_k)
            pool["v"] = pool["v"].at[:, phys[j]].set(blk_v)
        return self.policy.constrain_kv(pool)

    def chunk_fn(self):
        """Jitted chunked-prefill step: write one C-token chunk into the
        batch-1 dense scratch at ``offset``, attend over prefix+chunk, and
        return the logits at ``last_idx`` (the chunk's final real token).
        Shapes are (C, S) — prompt length never changes the graph."""
        policy = self.policy

        def build():
            def chunk(params, tokens, offset, scratch, last_idx):
                last, scratch = self.traced_chunk_step(params, scratch,
                                                       tokens[0], offset,
                                                       last_idx)
                return last, policy.constrain_kv(scratch)

            return jax.jit(chunk, donate_argnums=(3,))

        return self._build(("chunk", self.chunk), build)

    def gather_fn(self):
        """Jitted densify of ONE slot's table row into the scratch (prefix
        reuse: cached blocks → scratch so chunk prefill can attend them).
        An int8 pool dequantizes here — the scratch is always the model
        dtype, so chunk prefill attends exact dequantized values. The
        traced body derives the table width from the row argument (one
        cache entry regardless of width — it never changes mid-lifetime)."""
        s = self.ecfg.max_seq_len
        dt = self.cfg.dtype
        policy = self.policy

        def build():
            def gather(pool, row):
                # pool [L, N, BS, KH, D], row [MB] → dense [L, 1, S, KH,
                # D]. The row's final column is the ALWAYS-TRASH block —
                # slice it off so the densified prefix has the exact
                # scratch shape (an S+BS-wide scratch trips the rope-table
                # width validation when max_seq_len == the rope limit)
                def one(p, sc):
                    g = p[:, row]                    # [L, MB, BS, KH, D]
                    if sc is not None:
                        g = g.astype(jnp.float32) * sc[:, row][..., None]
                    l, mb_, bs, kh, d = g.shape
                    return g.astype(dt).reshape(
                        l, 1, mb_ * bs, kh, d)[:, :, :s]
                return policy.constrain_kv(
                    {"k": one(pool["k"], pool.get("k_scale")),
                     "v": one(pool["v"], pool.get("v_scale"))})

            return jax.jit(gather)

        return self._build("gather", build)

    def splice_fn(self):
        """Jitted copy of one chunk's blocks from the scratch into their
        physical pool blocks. C/BS is static → one graph."""
        return self._build("splice", lambda: jax.jit(
            self.traced_splice, donate_argnums=(0,)))

    def chunk_group_fn(self, g: int):
        """Fused admission graph (VERDICT r04 #6): lax.scan over ``g``
        chunks — each step chunk-prefills into the scratch AND splices its
        blocks into the pool. One dispatch replaces 2g, and the per-chunk
        host bookkeeping (table math, array uploads) collapses into one
        transfer of [g, ...] arrays. Returns the final chunk's last-token
        logits so the caller can sample the first output."""
        policy = self.policy

        def build():
            def group(params, pool, scratch, toks, offsets, last_idxs,
                      phys):
                # toks [g, C] offsets [g] last_idxs [g] phys [g, C/BS]
                def body(carry, xs):
                    pool, scratch = carry
                    tok, off, li, ph = xs
                    last, scratch = self.traced_chunk_step(
                        params, scratch, tok, off, li)
                    pool = self.traced_splice(
                        pool, scratch["k"], scratch["v"], off, ph)
                    return (pool, scratch), last

                (pool, scratch), lasts = jax.lax.scan(
                    body, (pool, scratch), (toks, offsets, last_idxs,
                                            phys))
                return pool, policy.constrain_kv(scratch), lasts[-1]

            return jax.jit(group, donate_argnums=(1, 2))

        return self._build(("chunkgroup", g), build)

    # -- compile-ahead (AOT) + static verification hooks ---------------------

    def lowering_jobs(self, params, kv_cache: Params, pool: Params,
                      scratch: Params, mb: int, buckets, spec_lens,
                      rng) -> Iterator[tuple]:
        """Enumerate every steady-state serving graph as ``(key, fn,
        abstract_args)`` — THE introspection surface (ISSUE 11): both
        :meth:`precompile` (lower+compile each job) and graphcheck's
        Pass A (lower each job and verify sharding/dtype/donation
        invariants from the jaxpr and compiled artifact) drive this one
        enumeration, so the verified signature set and the precompiled
        signature set cannot drift apart. Arguments may be real arrays or
        ``jax.ShapeDtypeStruct`` trees — only shapes/dtypes are read.
        Scalar positions yield concrete ints — the weak-typed aval the
        serve loop's python-int arguments produce."""
        policy = self.policy
        pspec = policy.abstract(params)
        b = self.ecfg.max_batch
        i32 = jnp.int32
        if self.chunk:
            bs = self.ecfg.kv_block_size
            c = self.chunk
            ascratch = policy.abstract(scratch, kv=True)
            apool = policy.abstract(pool, kv=True)
            yield (("chunk", c), self.chunk_fn(),
                   (pspec, jax.ShapeDtypeStruct((1, c), i32), 0, ascratch,
                    0))
            yield ("splice", self.splice_fn(),
                   (apool, ascratch["k"], ascratch["v"], 0,
                    jax.ShapeDtypeStruct((c // bs,), i32)))
            yield ("gather", self.gather_fn(),
                   (apool, jax.ShapeDtypeStruct((mb,), i32)))
            g = max(1, self.ecfg.admit_group_chunks)
            if g > 1:
                yield (("chunkgroup", g), self.chunk_group_fn(g),
                       (pspec, apool, ascratch,
                        jax.ShapeDtypeStruct((g, c), i32),
                        jax.ShapeDtypeStruct((g,), i32),
                        jax.ShapeDtypeStruct((g,), i32),
                        jax.ShapeDtypeStruct((g, c // bs), i32)))
        else:
            cfg = self.cfg
            for bucket in buckets:
                pre = jax.ShapeDtypeStruct(
                    (cfg.n_layers, 1, bucket, cfg.n_kv_heads,
                     cfg.head_dim), cfg.dtype)
                adense = policy.abstract(
                    {"k": kv_cache["k"], "v": kv_cache["v"]}, kv=True)
                yield (bucket, self.prefill_fn(bucket),
                       (pspec, jax.ShapeDtypeStruct((1, bucket), i32), 1))
                yield (("dsplice", bucket), self.dense_splice_fn(bucket),
                       (adense["k"], adense["v"], pre, pre, 0))
        kv_spec = policy.abstract(kv_cache, kv=True)
        arng = policy.abstract(rng)
        for k in self.ecfg.decode_steps:
            yield (("decode", k), self.decode_k(k),
                   (pspec, kv_spec, jax.ShapeDtypeStruct((b, 1), i32),
                    jax.ShapeDtypeStruct((b,), i32),
                    jax.ShapeDtypeStruct((b,), jnp.bool_),
                    arng))
        for s in spec_lens:
            yield (("verify", s), self.verify_fn(s),
                   (pspec, kv_spec, jax.ShapeDtypeStruct((b, 1), i32),
                    jax.ShapeDtypeStruct((b, s), i32),
                    jax.ShapeDtypeStruct((b,), i32),
                    jax.ShapeDtypeStruct((b,), jnp.bool_),
                    arng))

    def reachable_keys(self, buckets, spec_lens) -> set:
        """Every executable-cache key the serve loop can request in steady
        state — the OTHER half of graphcheck's closed-signature invariant
        (GRA005: this set must equal the :meth:`lowering_jobs` key set).

        One entry per dispatch site; when adding a dispatch that resolves
        a new key shape, extend BOTH this enumeration and
        ``lowering_jobs`` or the gate fails:

        - ``("decode", k)``: ``WindowScheduler.pick_steps`` and the
          admission-interleaved window pick only from
          ``ecfg.decode_steps``.
        - ``("verify", s)``: ``WindowScheduler.spec_room_len`` picks only
          from the engine's ``spec_lens`` buckets.
        - ``("chunk", c)`` / ``"splice"`` / ``"gather"``: paged admission
          — ONE validated chunk length; partial tail groups reuse these,
          never a fresh scan shape.
        - ``("chunkgroup", g)``: paged admission dispatches FULL groups
          only (``_admit_paged`` drops to the single-chunk graphs for
          tails).
        - ``bucket`` / ``("dsplice", bucket)``: dense admission buckets,
          clamped to max_seq_len by the engine (``_bucket_for``).
        """
        keys: set = {("decode", k) for k in self.ecfg.decode_steps}
        keys |= {("verify", s) for s in spec_lens}
        if self.chunk:
            keys |= {("chunk", self.chunk), "splice", "gather"}
            g = max(1, self.ecfg.admit_group_chunks)
            if g > 1:
                keys.add(("chunkgroup", g))
        else:
            for bucket in buckets:
                keys |= {bucket, ("dsplice", bucket)}
        return keys

    def precompile(self, params, kv_cache: Params, pool: Params,
                   scratch: Params, mb: int, buckets, spec_lens,
                   rng) -> dict:
        """AOT-compile every steady-state serving graph from SHAPES alone.

        XLA needs param shapes/dtypes, not values — so serving bring-up
        can run this concurrently with weight streaming (``params`` may be
        a ``jax.ShapeDtypeStruct`` tree) instead of serializing a
        multi-second compile behind the weight load. Each
        ``.lower(...).compile()`` executable replaces the jitted function
        under the same cache key the serve loop resolves. On a mesh
        policy the abstract specs carry NamedShardings, so the lowered
        executables are the exact SPMD programs the serve loop will
        dispatch. Seals the cache afterwards: any later miss is a
        recompile incident (counted + logged loudly)."""
        timings: dict[str, float] = {}
        for key, fn, args in self.lowering_jobs(
                params, kv_cache, pool, scratch, mb, buckets, spec_lens,
                rng):
            if not hasattr(fn, "lower"):
                continue                  # already an AOT executable
            t0 = time.perf_counter()
            self.compiled[key] = fn.lower(*args).compile()
            name = "_".join(str(p) for p in key) \
                if isinstance(key, tuple) else str(key)
            timings[f"compile_{name}_s"] = \
                round(time.perf_counter() - t0, 4)
        self.seal()
        return timings


def abstract_state(cfg, ecfg, policy, kv_quant: bool = False) -> dict:
    """Device-free abstract serving state for :meth:`GraphFactory.
    lowering_jobs`: the kv_cache/pool/scratch ``ShapeDtypeStruct`` trees
    an engine of this (model, engine-config) pair would hold, without
    allocating a byte. Shapes come from the same sources the engine uses
    (``KvPool`` for the paged pool, ``init_kv_cache`` via ``eval_shape``
    for dense/scratch), so graphcheck lowers EXACTLY the engine's graphs.
    Returns ``{"kv_cache", "pool", "scratch", "mb", "rng"}`` (paged) or
    the dense equivalents (empty pool/scratch, mb=0)."""
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    if ecfg.kv_block_size:
        from .kvpool import KvPool
        mgr = KvPool(cfg, ecfg, kv_quant, policy)
        kv_cache = mgr.array_specs()
        pool = {k: v for k, v in kv_cache.items() if k != "table"}
        scratch = jax.eval_shape(
            lambda: init_kv_cache(cfg, 1, ecfg.max_seq_len))
        return {"kv_cache": kv_cache, "pool": pool, "scratch": scratch,
                "mb": mgr.mb, "rng": rng}
    kv_cache = jax.eval_shape(
        lambda: init_kv_cache(cfg, ecfg.max_batch, ecfg.max_seq_len))
    return {"kv_cache": kv_cache, "pool": {}, "scratch": {}, "mb": 0,
            "rng": rng}
