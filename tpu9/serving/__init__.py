from .engine import EngineConfig, InferenceEngine

__all__ = ["EngineConfig", "InferenceEngine"]
