from .engine import EngineConfig, InferenceEngine
from .kvwire import KvWireError

__all__ = ["EngineConfig", "InferenceEngine", "KvWireError"]
