"""Paged KV-pool management for the serving engine (ISSUE 9 engine split).

The engine split's KV third: pool sizing (equal-HBM int8 auto sizing —
ISSUE 6), the trash-block discipline, slot→physical-block bookkeeping,
worst-case reservations and the host block table. Everything here is
HOST-side and topology-OBLIVIOUS: block ids are global integers, tables
are replicated, and admission/eviction arithmetic is identical on one
chip and on a tp×fsdp submesh — only the resident layout of the pool
arrays is sharded, and that placement goes through the
:mod:`tpu9.serving.shard` policy handed in at construction.

The allocator/prefix-cache primitives stay in :mod:`tpu9.serving.paged_kv`
(they predate the split and are imported by the router's admission math
via stats, not by code); this module owns their engine-side composition.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .paged_kv import BlockAllocator, PrefixCache, blocks_for

Params = dict[str, Any]


class KvPool:
    """One engine's paged KV pool: device arrays (built once via
    :meth:`init_arrays`), the block allocator + prefix cache, and the
    per-slot physical-block state the serve loop mutates."""

    def __init__(self, cfg, ecfg, kv_quant: bool, policy):
        b, s = ecfg.max_batch, ecfg.max_seq_len
        bs = ecfg.kv_block_size
        self.cfg = cfg
        self.ecfg = ecfg
        self.kv_quant = kv_quant
        self.policy = policy
        if ecfg.kv_pool_blocks:
            base_blocks = ecfg.kv_pool_blocks
        else:
            base_blocks = b * s // bs            # dense parity
            if kv_quant:
                # equal-HBM sizing: the int8 pool spends the same bytes
                # the bf16 pool would have — ~2x the blocks, which is the
                # whole point (capacity == admission headroom == the
                # router's kv_blocks signal)
                from .paged_kv import kv_block_bytes
                base_blocks = (base_blocks
                               * kv_block_bytes(cfg, bs, False)
                               // kv_block_bytes(cfg, bs, True))
        # +1: one dedicated TRASH block absorbs splice writes of the
        # padded tail of a non-block-aligned final chunk
        self.n_blocks = base_blocks + 1
        # table width: +1 ALWAYS-TRASH column — a decode write at
        # position S (cache full; callers should bound it, but a
        # regression must not corrupt data) computes pos // bs == S/bs
        # which would otherwise CLAMP onto the last real block and
        # overwrite valid KV; the extra column absorbs it harmlessly
        # (attention masks by cache_len, so it is never read)
        self.mb = s // bs + 1                    # table width
        self.allocator = BlockAllocator(self.n_blocks, bs)
        self.trash_block = self.allocator.alloc(1)[0]
        # inactive decode lanes scatter through their (zero-padded) table
        # rows every step — push_table pads rows with the trash block
        # explicitly, but the freshly-zeroed initial table relies on the
        # trash block being physical block 0
        assert self.trash_block == 0, self.trash_block
        # the trash block is held forever — reservations must not count
        # on it
        self.allocator.reserve_capacity = self.n_blocks - 1
        self.prefix_cache = PrefixCache(self.allocator,
                                        ecfg.prefix_cache_blocks)
        self.slot_blocks: list[list[int]] = [[] for _ in range(b)]
        self.slot_reserved = [0] * b
        self.table_np = np.zeros((b, self.mb), dtype=np.int32)
        self.kv_allocs = 0           # lifetime block allocations

    def array_shapes(self) -> dict:
        """``name -> (shape, dtype)`` for every pool array — the ONE shape
        source :meth:`init_arrays` allocates from and
        :meth:`array_specs` abstracts from (they cannot drift)."""
        import jax.numpy as jnp
        cfg, ecfg = self.cfg, self.ecfg
        pool_shape = (cfg.n_layers, self.n_blocks, ecfg.kv_block_size,
                      cfg.n_kv_heads, cfg.head_dim)
        dt = jnp.int8 if self.kv_quant else cfg.dtype
        shapes = {"k": (pool_shape, dt), "v": (pool_shape, dt),
                  "table": (self.table_np.shape, jnp.int32)}
        if self.kv_quant:
            # per-(position, head) f32 absmax scales alongside the pool
            # (ops.quant.quantize_kv) — same [N, BS, KH] indexing as the
            # payload so every write/read shares the table math
            sc_shape = pool_shape[:-1]
            shapes["k_scale"] = (sc_shape, jnp.float32)
            shapes["v_scale"] = (sc_shape, jnp.float32)
        return shapes

    def init_arrays(self) -> Params:
        """The pool's device state: payload (+ int8 scale planes) and the
        block table — placed through the sharding policy (head axis over
        tp on a mesh; plain single-device arrays otherwise)."""
        kv = {name: self.policy.zeros(shape, dt, name)
              for name, (shape, dt) in self.array_shapes().items()
              if name != "table"}
        kv["table"] = self.policy.device_table(self.table_np)
        return kv

    def array_specs(self) -> Params:
        """Abstract (``jax.ShapeDtypeStruct``) twin of :meth:`init_arrays`
        — the device-free face graphcheck and compile-ahead lower
        against. Plain structs, no shardings: callers route them through
        ``policy.abstract(..., kv=True)`` exactly as the engine does."""
        import jax
        return {name: jax.ShapeDtypeStruct(shape, dt)
                for name, (shape, dt) in self.array_shapes().items()}

    # -- block allocation ----------------------------------------------------

    def alloc_blocks(self, n: int) -> list[int]:
        """Allocate physical blocks; evicts prefix-cache holdings if the
        free list runs short. Reservations make failure impossible."""
        if n <= 0:
            return []
        got = self.allocator.alloc(n)
        if got is None:
            self.prefix_cache.evict_for_space(n)
            got = self.allocator.alloc(n)
        if got is None:
            raise RuntimeError(
                f"KV pool exhausted: need {n}, free "
                f"{self.allocator.free_count} (reservation bug)")
        self.kv_allocs += n
        return got

    # -- kvwire export / import (ISSUE 16) -----------------------------------

    def wire_names(self) -> list[str]:
        """Pool arrays that ship on the wire (payload + scale planes;
        the table is host bookkeeping — block ids are pool-local)."""
        return [n for n in self.array_shapes() if n != "table"]

    def export_blocks(self, kv, blocks: list[int], prefix_key: bytes,
                      n_tokens: int) -> bytes:
        """Gather ``blocks`` of every pool plane into one kvwire payload.
        Planes come out CANONICAL (full-head) via ``policy.gather_kv``,
        so the payload is topology-independent. The caller must hold a
        pin on the blocks for the duration (prefix-cache export pin or a
        slot's own refs) — the gather syncs the device and an eviction
        interleaved at that boundary must not recycle them."""
        from . import kvwire
        meta = kvwire.geometry(self.cfg, self.ecfg, self.kv_quant)
        meta.update({"n_blocks": len(blocks), "n_tokens": int(n_tokens),
                     "prefix_key": prefix_key.hex(),
                     "topology": self.policy.describe()})
        idx = np.asarray(blocks, dtype=np.int32)
        planes = {name: self.policy.gather_kv(name, kv[name])[:, idx]
                  for name in self.wire_names()}
        return kvwire.encode_blocks(meta, planes)

    def import_blocks(self, kv, payload: bytes):
        """Validate + splice a kvwire payload into fresh pool blocks and
        adopt them into the prefix cache under the exporter's key.

        Returns ``(kv, adopted, header)`` — ``kv`` rebound with the
        written (and re-placed) planes. All validation happens BEFORE
        any allocation or write: a bad payload leaves the pool
        untouched. ``adopted=False`` means the entry could not fit the
        prefix budget (blocks were released; caller falls back to
        re-prefill)."""
        import jax.numpy as jnp

        from . import kvwire
        header, planes = kvwire.decode_blocks(payload)
        kvwire.check_geometry(
            header, kvwire.geometry(self.cfg, self.ecfg, self.kv_quant))
        try:
            nb = int(header["n_blocks"])
            n_tokens = int(header["n_tokens"])
            key = bytes.fromhex(header["prefix_key"])
        except (KeyError, TypeError, ValueError) as exc:
            raise kvwire.KvWireError(
                f"kvwire: missing/malformed prefix metadata: {exc}") from exc
        if nb <= 0 or not key:
            raise kvwire.KvWireError(
                f"kvwire: empty prefix payload (n_blocks={nb})")
        shapes = self.array_shapes()
        for name in self.wire_names():
            if name not in planes:
                raise kvwire.KvWireError(
                    f"kvwire: payload missing plane {name!r}")
            want = (shapes[name][0][0], nb) + tuple(shapes[name][0][2:])
            if tuple(planes[name].shape) != want:
                raise kvwire.KvWireError(
                    f"kvwire: plane {name!r} shape "
                    f"{tuple(planes[name].shape)} != pool slice {want}")
        if self.prefix_cache.contains(key):
            # this replica already holds the prefix (raced a local
            # prefill): the adopt is a no-op hit, zero pool work
            return kv, True, header
        blocks = self.alloc_blocks(nb)
        try:
            idx = jnp.asarray(blocks, dtype=jnp.int32)
            new_kv = dict(kv)
            for name in self.wire_names():
                arr = jnp.asarray(np.ascontiguousarray(planes[name]),
                                  dtype=shapes[name][1])
                new_kv[name] = new_kv[name].at[:, idx].set(arr)
            # re-pin the resident layout: the scatter above lets GSPMD
            # infer an output sharding; place_kv restores the declared
            # head-axis layout (identity on one chip)
            placed = self.policy.place_kv(
                {n: new_kv[n] for n in self.wire_names()})
            new_kv.update(placed)
        except Exception:
            self.allocator.release(blocks)
            raise
        if not self.prefix_cache.adopt(key, blocks, n_tokens):
            self.allocator.release(blocks)
            return new_kv, False, header
        return new_kv, True, header

    # -- the host block table ------------------------------------------------

    def device_table(self):
        return self.policy.device_table(self.table_np)

    def push_table(self, slot: int):
        """Refresh one slot's table row from its block list (trash-padded)
        and return the new device table for the engine to install."""
        row = np.full((self.mb,), self.trash_block, dtype=np.int32)
        blocks = self.slot_blocks[slot]
        row[:len(blocks)] = blocks
        self.table_np[slot] = row
        return self.device_table()

    def ensure_slot_blocks(self, slot: int, n_tokens: int) -> bool:
        """Grow the slot's physical block list to cover ``n_tokens``
        positions. Returns True when the table changed (the caller must
        install :meth:`device_table` / the value from :meth:`push_table`)."""
        need = blocks_for(n_tokens, self.ecfg.kv_block_size)
        have = len(self.slot_blocks[slot])
        if need <= have:
            return False
        self.slot_blocks[slot].extend(self.alloc_blocks(need - have))
        return True

    def release_slot(self, slot: int):
        """Retirement: physical blocks back to the pool (prefix-cache refs
        keep shared prefix blocks alive), worst-case reservation released.
        Returns the refreshed device table."""
        self.allocator.release(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        table = self.push_table(slot)
        self.allocator.unreserve(self.slot_reserved[slot])
        self.slot_reserved[slot] = 0
        return table
