"""Paged KV-pool management for the serving engine (ISSUE 9 engine split).

The engine split's KV third: pool sizing (equal-HBM int8 auto sizing —
ISSUE 6), the trash-block discipline, slot→physical-block bookkeeping,
worst-case reservations and the host block table. Everything here is
HOST-side and topology-OBLIVIOUS: block ids are global integers, tables
are replicated, and admission/eviction arithmetic is identical on one
chip and on a tp×fsdp submesh — only the resident layout of the pool
arrays is sharded, and that placement goes through the
:mod:`tpu9.serving.shard` policy handed in at construction.

The allocator/prefix-cache primitives stay in :mod:`tpu9.serving.paged_kv`
(they predate the split and are imported by the router's admission math
via stats, not by code); this module owns their engine-side composition.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Optional

import numpy as np

from .paged_kv import (BlockAllocator, PrefixCache, blocks_for,
                       kv_block_bytes)

Params = dict[str, Any]

# host-tier spill scoring (ISSUE 20): a reaped host entry whose
# hits×recency score clears this goes to the peer cache instead of
# dying — system prompts and chat-session heads score high, one-shot
# prompts decay to zero and are simply dropped
PEER_SPILL_SCORE = 1.0
PEER_SPILL_HALF_LIFE_S = 300.0
PEER_SPILL_QUEUE_MAX = 8


class HostKvTier:
    """Host-DRAM second tier for the paged KV pool (ISSUE 20).

    Stores CANONICAL (full-head, topology-independent) pool planes per
    prefix key as plain numpy — the same layout ``kvwire`` ships — so a
    down-page is one gather off the device, an up-page is one
    policy-placed scatter back, and a peer-tier spill is a pure host
    ``kvwire.encode_blocks`` with zero device work. With ``kv_quant``
    the planes are int8 (+f32 scales), so host DRAM holds ~2× the
    prefixes the same bytes would in bf16.

    Byte budget is enforced on insert: LRU entries are reaped (the pool
    scores them for peer spill first). Pinned prefix-cache entries are
    never reaped — the ``skip`` predicate wires that in."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        # key -> {"planes", "n_tokens", "n_blocks", "nbytes"}
        self._entries: "collections.OrderedDict[bytes, dict]" = \
            collections.OrderedDict()
        self.used_bytes = 0
        self.inserts = 0
        self.evictions = 0
        self.rejected = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    def get(self, key: bytes) -> Optional[dict]:
        ent = self._entries.get(key)
        if ent is not None:
            self._entries.move_to_end(key)
        return ent

    def peek(self, key: bytes) -> Optional[dict]:
        return self._entries.get(key)

    def pop(self, key: bytes) -> Optional[dict]:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self.used_bytes -= ent["nbytes"]
        return ent

    def put(self, key: bytes, planes: dict, n_tokens: int,
            n_blocks: int, skip=None) -> tuple[bool, list]:
        """Insert (or refresh) an entry, reaping LRU entries to fit.
        Returns ``(stored, reaped)`` where ``reaped`` is the list of
        ``(key, entry)`` pairs evicted to make room — the pool scores
        those for peer spill. ``skip(key)`` excludes unpinned-unsafe
        entries from reaping."""
        if key in self._entries:
            self.pop(key)
        nbytes = sum(int(p.nbytes) for p in planes.values())
        if nbytes > self.capacity_bytes:
            self.rejected += 1
            return False, []
        reaped: list = []
        while self.used_bytes + nbytes > self.capacity_bytes:
            victim = self._reap_one(skip)
            if victim is None:
                self.rejected += 1
                # put back nothing; entry does not fit without touching
                # skip-protected residents
                return False, reaped
            reaped.append(victim)
        self._entries[key] = {"planes": planes, "n_tokens": int(n_tokens),
                              "n_blocks": int(n_blocks), "nbytes": nbytes}
        self.used_bytes += nbytes
        self.inserts += 1
        return True, reaped

    def _reap_one(self, skip=None):
        for key in self._entries:           # OrderedDict: LRU first
            if skip is not None and skip(key):
                continue
            ent = self.pop(key)
            self.evictions += 1
            return key, ent
        return None

    def stats(self) -> dict:
        return {"entries": len(self._entries),
                "bytes": self.used_bytes,
                "capacity_bytes": self.capacity_bytes,
                "inserts": self.inserts, "evictions": self.evictions,
                "rejected": self.rejected}


class KvPool:
    """One engine's paged KV pool: device arrays (built once via
    :meth:`init_arrays`), the block allocator + prefix cache, and the
    per-slot physical-block state the serve loop mutates."""

    def __init__(self, cfg, ecfg, kv_quant: bool, policy,
                 host_pool_mb: int = 0):
        b, s = ecfg.max_batch, ecfg.max_seq_len
        bs = ecfg.kv_block_size
        self.cfg = cfg
        self.ecfg = ecfg
        self.kv_quant = kv_quant
        self.policy = policy
        if ecfg.kv_pool_blocks:
            base_blocks = ecfg.kv_pool_blocks
        else:
            base_blocks = b * s // bs            # dense parity
            if kv_quant:
                # equal-HBM sizing: the int8 pool spends the same bytes
                # the bf16 pool would have — ~2x the blocks, which is the
                # whole point (capacity == admission headroom == the
                # router's kv_blocks signal)
                from .paged_kv import kv_block_bytes
                base_blocks = (base_blocks
                               * kv_block_bytes(cfg, bs, False)
                               // kv_block_bytes(cfg, bs, True))
        # +1: one dedicated TRASH block absorbs splice writes of the
        # padded tail of a non-block-aligned final chunk
        self.n_blocks = base_blocks + 1
        # table width: +1 ALWAYS-TRASH column — a decode write at
        # position S (cache full; callers should bound it, but a
        # regression must not corrupt data) computes pos // bs == S/bs
        # which would otherwise CLAMP onto the last real block and
        # overwrite valid KV; the extra column absorbs it harmlessly
        # (attention masks by cache_len, so it is never read)
        self.mb = s // bs + 1                    # table width
        self.allocator = BlockAllocator(self.n_blocks, bs)
        self.trash_block = self.allocator.alloc(1)[0]
        # inactive decode lanes scatter through their (zero-padded) table
        # rows every step — push_table pads rows with the trash block
        # explicitly, but the freshly-zeroed initial table relies on the
        # trash block being physical block 0
        assert self.trash_block == 0, self.trash_block
        # the trash block is held forever — reservations must not count
        # on it
        self.allocator.reserve_capacity = self.n_blocks - 1
        self.prefix_cache = PrefixCache(self.allocator,
                                        ecfg.prefix_cache_blocks)
        self.slot_blocks: list[list[int]] = [[] for _ in range(b)]
        self.slot_reserved = [0] * b
        self.table_np = np.zeros((b, self.mb), dtype=np.int32)
        self.kv_allocs = 0           # lifetime block allocations
        # -- host-DRAM second tier (ISSUE 20); inert at 0 MB -----------------
        self.host_pool_mb = int(host_pool_mb)
        self.host_tier: Optional[HostKvTier] = None
        self.downpages = 0
        self.uppages = 0
        self.peer_spills = 0
        # (key_hex, payload, n_tokens) encoded for the peer cache; the
        # runner drains this — the serving plane never touches transport
        self.peer_spill_queue: collections.deque = \
            collections.deque(maxlen=PEER_SPILL_QUEUE_MAX)
        # kv_tier decision journal (ISSUE 19/20): plain dicts the RUNNER
        # drains into the decision ledger on its heartbeat loop — the
        # serving plane must not import tpu9.observability.decisions
        # (BND001), the same one-way evidence flow as spans and health
        self.kv_decisions: collections.deque = collections.deque(maxlen=256)
        if self.host_pool_mb > 0:
            self.host_tier = HostKvTier(self.host_pool_mb * (1 << 20))
            # an entry re-prefilled on-device drops its stale host copy
            self.prefix_cache.on_host_drop = self.host_tier.pop

    def array_shapes(self) -> dict:
        """``name -> (shape, dtype)`` for every pool array — the ONE shape
        source :meth:`init_arrays` allocates from and
        :meth:`array_specs` abstracts from (they cannot drift)."""
        import jax.numpy as jnp
        cfg, ecfg = self.cfg, self.ecfg
        pool_shape = (cfg.n_layers, self.n_blocks, ecfg.kv_block_size,
                      cfg.n_kv_heads, cfg.head_dim)
        dt = jnp.int8 if self.kv_quant else cfg.dtype
        shapes = {"k": (pool_shape, dt), "v": (pool_shape, dt),
                  "table": (self.table_np.shape, jnp.int32)}
        if self.kv_quant:
            # per-(position, head) f32 absmax scales alongside the pool
            # (ops.quant.quantize_kv) — same [N, BS, KH] indexing as the
            # payload so every write/read shares the table math
            sc_shape = pool_shape[:-1]
            shapes["k_scale"] = (sc_shape, jnp.float32)
            shapes["v_scale"] = (sc_shape, jnp.float32)
        return shapes

    def init_arrays(self) -> Params:
        """The pool's device state: payload (+ int8 scale planes) and the
        block table — placed through the sharding policy (head axis over
        tp on a mesh; plain single-device arrays otherwise)."""
        kv = {name: self.policy.zeros(shape, dt, name)
              for name, (shape, dt) in self.array_shapes().items()
              if name != "table"}
        kv["table"] = self.policy.device_table(self.table_np)
        return kv

    def array_specs(self) -> Params:
        """Abstract (``jax.ShapeDtypeStruct``) twin of :meth:`init_arrays`
        — the device-free face graphcheck and compile-ahead lower
        against. Plain structs, no shardings: callers route them through
        ``policy.abstract(..., kv=True)`` exactly as the engine does."""
        import jax
        return {name: jax.ShapeDtypeStruct(shape, dt)
                for name, (shape, dt) in self.array_shapes().items()}

    # -- block allocation ----------------------------------------------------

    def alloc_blocks(self, n: int) -> list[int]:
        """Allocate physical blocks; evicts prefix-cache holdings if the
        free list runs short. Reservations make failure impossible."""
        if n <= 0:
            return []
        got = self.allocator.alloc(n)
        if got is None:
            self.prefix_cache.evict_for_space(n)
            got = self.allocator.alloc(n)
        if got is None:
            raise RuntimeError(
                f"KV pool exhausted: need {n}, free "
                f"{self.allocator.free_count} (reservation bug)")
        self.kv_allocs += n
        return got

    # -- kvwire export / import (ISSUE 16) -----------------------------------

    def wire_names(self) -> list[str]:
        """Pool arrays that ship on the wire (payload + scale planes;
        the table is host bookkeeping — block ids are pool-local)."""
        return [n for n in self.array_shapes() if n != "table"]

    def export_blocks(self, kv, blocks: list[int], prefix_key: bytes,
                      n_tokens: int) -> bytes:
        """Gather ``blocks`` of every pool plane into one kvwire payload.
        Planes come out CANONICAL (full-head) via ``policy.gather_kv``,
        so the payload is topology-independent. The caller must hold a
        pin on the blocks for the duration (prefix-cache export pin or a
        slot's own refs) — the gather syncs the device and an eviction
        interleaved at that boundary must not recycle them."""
        from . import kvwire
        meta = kvwire.geometry(self.cfg, self.ecfg, self.kv_quant)
        meta.update({"n_blocks": len(blocks), "n_tokens": int(n_tokens),
                     "prefix_key": prefix_key.hex(),
                     "topology": self.policy.describe()})
        idx = np.asarray(blocks, dtype=np.int32)
        planes = {name: self.policy.gather_kv(name, kv[name])[:, idx]
                  for name in self.wire_names()}
        return kvwire.encode_blocks(meta, planes)

    def import_blocks(self, kv, payload: bytes):
        """Validate + splice a kvwire payload into fresh pool blocks and
        adopt them into the prefix cache under the exporter's key.

        Returns ``(kv, adopted, header)`` — ``kv`` rebound with the
        written (and re-placed) planes. All validation happens BEFORE
        any allocation or write: a bad payload leaves the pool
        untouched. ``adopted=False`` means the entry could not fit the
        prefix budget (blocks were released; caller falls back to
        re-prefill)."""
        from . import kvwire
        header, planes = kvwire.decode_blocks(payload)
        kvwire.check_geometry(
            header, kvwire.geometry(self.cfg, self.ecfg, self.kv_quant))
        try:
            nb = int(header["n_blocks"])
            n_tokens = int(header["n_tokens"])
            key = bytes.fromhex(header["prefix_key"])
        except (KeyError, TypeError, ValueError) as exc:
            raise kvwire.KvWireError(
                f"kvwire: missing/malformed prefix metadata: {exc}") from exc
        if nb <= 0 or not key:
            raise kvwire.KvWireError(
                f"kvwire: empty prefix payload (n_blocks={nb})")
        shapes = self.array_shapes()
        for name in self.wire_names():
            if name not in planes:
                raise kvwire.KvWireError(
                    f"kvwire: payload missing plane {name!r}")
            want = (shapes[name][0][0], nb) + tuple(shapes[name][0][2:])
            if tuple(planes[name].shape) != want:
                raise kvwire.KvWireError(
                    f"kvwire: plane {name!r} shape "
                    f"{tuple(planes[name].shape)} != pool slice {want}")
        if self.prefix_cache.contains(key):
            # this replica already holds the prefix (raced a local
            # prefill): the adopt is a no-op hit, zero pool work
            return kv, True, header
        blocks = self.alloc_blocks(nb)
        try:
            new_kv = self.place_host_blocks(kv, planes, blocks)
        except Exception:
            self.allocator.release(blocks)
            raise
        if not self.prefix_cache.adopt(key, blocks, n_tokens):
            self.allocator.release(blocks)
            return new_kv, False, header
        return new_kv, True, header

    def place_host_blocks(self, kv, planes: dict, blocks: list[int]):
        """Splice canonical host planes into ``blocks`` of every pool
        array and re-pin the resident layout through the sharding policy
        (head axis over tp on a mesh; identity on one chip). Shared by
        kvwire import and the host-tier up-page — one scatter path means
        the MeshPolicy bit-exactness proof covers both."""
        import jax.numpy as jnp
        shapes = self.array_shapes()
        idx = jnp.asarray(blocks, dtype=jnp.int32)
        new_kv = dict(kv)
        for name in self.wire_names():
            arr = jnp.asarray(np.ascontiguousarray(planes[name]),
                              dtype=shapes[name][1])
            new_kv[name] = new_kv[name].at[:, idx].set(arr)
        # the scatter above lets GSPMD infer an output sharding;
        # place_kv restores the declared head-axis layout
        placed = self.policy.place_kv(
            {n: new_kv[n] for n in self.wire_names()})
        new_kv.update(placed)
        return new_kv

    # -- host-DRAM tier: down-page / up-page / peer spill (ISSUE 20) ---------

    @property
    def tiered(self) -> bool:
        return self.host_tier is not None

    def downpage(self, kv, entry) -> bool:
        """Move one unpinned device prefix entry to the host tier:
        gather its blocks' canonical planes to host DRAM, release the
        pool blocks, keep the entry alive under ``tier="host"``. Called
        at window boundaries only — the gather is a device sync and must
        never sit on the per-token path. False = the host tier could not
        fit it (the caller lets ``_evict_one`` destroy it as before)."""
        if self.host_tier is None or entry.pins or not entry.blocks:
            return False
        idx = np.asarray(entry.blocks, dtype=np.int32)  # tpu9: noqa[JAX001] host-side block-index list, no device value involved
        planes = {
            name: np.asarray(self.policy.gather_kv(name, kv[name])[:, idx])  # tpu9: noqa[JAX001] intended sync point: window-boundary down-page gather (same class as the drain's batched device_get)
            for name in self.wire_names()}
        stored, reaped = self.host_tier.put(
            entry.key, planes, entry.n_tokens, len(entry.blocks),
            skip=self._host_pin_guard)
        self._reap_to_peer(reaped)
        if not stored:
            return False
        self.prefix_cache.spill_to_host(entry)
        self.downpages += 1
        return True

    def _host_pin_guard(self, key: bytes) -> bool:
        """Host-tier reap skip predicate: a pinned host entry has an
        up-page in flight — its planes must not vanish mid-copy."""
        ent = self.prefix_cache._entries.get(key)
        return ent is not None and ent.pins > 0

    def uppage_planes(self, entry) -> Optional[dict]:
        """The host planes backing a host-tier entry (None = lost a race
        with a host reap; caller degrades to recompute)."""
        if self.host_tier is None:
            return None
        ent = self.host_tier.get(entry.key)
        return None if ent is None else ent["planes"]

    def complete_uppage(self, kv, entry, planes: dict):
        """Finish an up-page: scatter the planes into freshly-allocated
        blocks via the sharding policy and promote the entry back to
        device residency. Returns the rebound ``kv``. The entry must be
        PINNED by the caller for the whole up-page (lookup pins it)."""
        blocks = self.alloc_blocks(len(entry.blocks) or
                                   blocks_for(entry.n_tokens,
                                              self.ecfg.kv_block_size))
        try:
            new_kv = self.place_host_blocks(kv, planes, blocks)
        except Exception:
            self.allocator.release(blocks)
            raise
        self.prefix_cache.promote_to_device(entry, blocks)
        if self.host_tier is not None:
            self.host_tier.pop(entry.key)
        self.uppages += 1
        return new_kv

    def _reap_to_peer(self, reaped: list) -> None:
        """Score host-tier reap victims on the hits×recency clock;
        winners serialize through kvwire onto the peer-spill queue (the
        runner ships them under the ``kv:`` namespace), losers die and
        their prefix-cache entries are journaled as evicted. Either way
        the choice leaves a ``kv_tier`` decision record."""
        from . import kvwire
        now = time.monotonic()
        for key, ent in reaped:
            pe = self.prefix_cache._entries.get(key)
            score = 0.0
            if pe is not None:
                age = max(0.0, now - pe.last_used)
                score = pe.hits * 0.5 ** (age / PEER_SPILL_HALF_LIFE_S)
            if score >= PEER_SPILL_SCORE:
                meta = kvwire.geometry(self.cfg, self.ecfg, self.kv_quant)
                meta.update({"n_blocks": ent["n_blocks"],
                             "n_tokens": ent["n_tokens"],
                             "prefix_key": key.hex(),
                             "topology": self.policy.describe()})
                payload = kvwire.encode_blocks(meta, ent["planes"])
                self.peer_spill_queue.append(
                    (key.hex()[:16], payload, ent["n_tokens"]))
                self.peer_spills += 1
                self.prefix_cache.drop(key, kind="peer")
                self.kv_decisions.append(
                    {"decision": "spill",
                     "chosen": f"peer:{key.hex()[:16]}",
                     "signals": {"score": round(score, 4),
                                 "n_tokens": ent["n_tokens"]}})
            else:
                self.prefix_cache.drop(key, kind="evict")
                self.kv_decisions.append(
                    {"decision": "evict", "chosen": "drop",
                     "rejected": [{"alternative": f"peer:{key.hex()[:16]}",
                                   "reason": "score_below_spill_threshold"}],
                     "signals": {"score": round(score, 4),
                                 "n_tokens": ent["n_tokens"]}})

    def drain_peer_spills(self) -> list:
        """Hand the queued peer-cache payloads to the transport owner
        (the runner). Destructive read; bounded by the deque cap."""
        out = list(self.peer_spill_queue)
        self.peer_spill_queue.clear()
        return out

    def tier_stats(self) -> dict:
        """Flat occupancy/counter snapshot for the ``kvtier_`` stats
        family (bytes price the DEVICE pool dtype for the device side
        and actual numpy bytes for the host side)."""
        bb = kv_block_bytes(self.cfg, self.ecfg.kv_block_size,
                            self.kv_quant)
        held = self.prefix_cache.held_blocks
        out = {"device_blocks": held, "device_bytes": held * bb,
               "downpages": self.downpages, "uppages": self.uppages,
               "peer_spills": self.peer_spills,
               "host_blocks": 0, "host_bytes": 0, "host_entries": 0,
               "host_evictions": 0}
        if self.host_tier is not None:
            hs = self.host_tier.stats()
            out.update({
                "host_bytes": hs["bytes"], "host_entries": hs["entries"],
                "host_blocks": sum(e["n_blocks"] for e in
                                   self.host_tier._entries.values()),
                "host_evictions": hs["evictions"]})
        return out

    # -- the host block table ------------------------------------------------

    def device_table(self):
        return self.policy.device_table(self.table_np)

    def push_table(self, slot: int):
        """Refresh one slot's table row from its block list (trash-padded)
        and return the new device table for the engine to install."""
        row = np.full((self.mb,), self.trash_block, dtype=np.int32)
        blocks = self.slot_blocks[slot]
        row[:len(blocks)] = blocks
        self.table_np[slot] = row
        return self.device_table()

    def ensure_slot_blocks(self, slot: int, n_tokens: int) -> bool:
        """Grow the slot's physical block list to cover ``n_tokens``
        positions. Returns True when the table changed (the caller must
        install :meth:`device_table` / the value from :meth:`push_table`)."""
        need = blocks_for(n_tokens, self.ecfg.kv_block_size)
        have = len(self.slot_blocks[slot])
        if need <= have:
            return False
        self.slot_blocks[slot].extend(self.alloc_blocks(need - have))
        return True

    def release_slot(self, slot: int):
        """Retirement: physical blocks back to the pool (prefix-cache refs
        keep shared prefix blocks alive), worst-case reservation released.
        Returns the refreshed device table."""
        self.allocator.release(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        table = self.push_table(slot)
        self.allocator.unreserve(self.slot_reserved[slot])
        self.slot_reserved[slot] = 0
        return table
