"""Serializable paged-KV blocks (ISSUE 16): one versioned wire format
powering disaggregated prefill/decode, drain migration and block-ship
failover resume.

The ``.tpu9w`` v1/v2 discipline applied to KV: a payload either parses
completely against a version this reader knows, or fails loudly BEFORE
any pool mutation — never a mid-import KeyError with half a prefix
spliced into the cache.

Format v1 (little-endian)::

    magic    b"TPU9KV\\0"          7 bytes
    version  u16                   = 1
    hlen     u32                   header JSON byte length
    header   JSON (utf-8)
    planes   raw plane bytes, concatenated in header["planes"] order

Header fields:

- geometry: ``n_layers``, ``kv_block_size``, ``n_kv_heads``,
  ``head_dim``, ``kv_dtype`` ("bfloat16" | "int8" | ...) — must match
  the importing pool exactly (block ids are meaningless across
  geometries);
- ``n_blocks`` / ``n_tokens`` / ``prefix_key`` (hex sha1 of the
  block-aligned token prefix, :meth:`PrefixCache._key`) — what the
  importer adopts into its prefix cache;
- ``topology`` (``policy.describe()``) — informational: planes are
  always CANONICAL full-head arrays (``[L, nb, BS, KH, D]`` payload,
  ``[L, nb, BS, KH]`` f32 scales), because export gathers head shards
  through the shard policy and import re-places through it. A tp=2
  exporter and a tp=1 importer interoperate byte-for-byte;
- ``planes``: ordered ``{name, dtype, shape, nbytes}`` records.

Transport is NOT this module's business: payloads ride the existing
``CacheClient`` hedged-read path under the ``kv:`` namespace
(content-addressed — peer verification requires plain chunk digests).
BND001 restricts importers to kvpool/engine/runner/cache/bench: the
router and gateway speak policy (flags, keys, token counts), never
payloads.
"""

from __future__ import annotations

import json
import struct

import numpy as np

MAGIC = b"TPU9KV\x00"
FORMAT_VERSION = 1
# cache-plane namespace prefix for shipped blocks (the digest itself
# stays a plain content hash — hedged peer reads verify it)
KV_NAMESPACE = "kv"

_PRELUDE = struct.Struct("<7sHI")          # magic, version, header length

# plane dtypes this reader will materialize. An unlisted dtype in a
# well-formed v1 header is a forward-compat failure, reported as such.
_DTYPES = ("bfloat16", "float32", "float16", "int8", "int32")


class KvWireError(ValueError):
    """Malformed / unsupported / geometry-mismatched KV payload."""


def _np_dtype(name: str) -> np.dtype:
    if name not in _DTYPES:
        raise KvWireError(f"kvwire: unsupported plane dtype {name!r} "
                          f"(supported: {', '.join(_DTYPES)})")
    if name == "bfloat16":
        import jax.numpy as jnp
        return np.dtype(jnp.bfloat16)
    return np.dtype(name)


def geometry(cfg, ecfg, kv_quant: bool) -> dict:
    """The pool-identity fields import refuses to cross."""
    return {"n_layers": int(cfg.n_layers),
            "kv_block_size": int(ecfg.kv_block_size),
            "n_kv_heads": int(cfg.n_kv_heads),
            "head_dim": int(cfg.head_dim),
            "kv_dtype": "int8" if kv_quant else np.dtype(cfg.dtype).name}


def check_geometry(header: dict, geo: dict) -> None:
    """Every mismatch in one error — a cross-deployment ship failure
    should read like a diff, not a scavenger hunt."""
    bad = [f"{k}: payload={header.get(k)!r} pool={v!r}"
           for k, v in geo.items() if header.get(k) != v]
    if bad:
        raise KvWireError("kvwire: pool geometry mismatch ("
                          + "; ".join(bad) + ")")


def encode_blocks(meta: dict, planes: dict[str, np.ndarray]) -> bytes:
    """``meta`` (geometry + prefix metadata + topology) + canonical
    host planes → one self-describing payload."""
    header = dict(meta)
    records = []
    blobs = []
    for name, arr in planes.items():
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        records.append({"name": name, "dtype": arr.dtype.name,
                        "shape": list(arr.shape), "nbytes": len(raw)})
        blobs.append(raw)
    header["planes"] = records
    hjson = json.dumps(header, sort_keys=True).encode()
    return b"".join([_PRELUDE.pack(MAGIC, FORMAT_VERSION, len(hjson)),
                     hjson] + blobs)


def decode_header(data: bytes) -> tuple[dict, int]:
    """(header, plane-bytes offset). Version/shape gates live here so
    both full decodes and header-only peeks fail identically."""
    if len(data) < _PRELUDE.size:
        raise KvWireError(f"kvwire: payload truncated at {len(data)} "
                          f"bytes (prelude is {_PRELUDE.size})")
    magic, version, hlen = _PRELUDE.unpack_from(data)
    if magic != MAGIC:
        raise KvWireError("kvwire: bad magic (not a KV block payload)")
    if version != FORMAT_VERSION:
        raise KvWireError(
            f"kvwire: unsupported format version {version} (this reader "
            f"speaks v{FORMAT_VERSION}; refusing to guess at a newer "
            "layout)")
    off = _PRELUDE.size + hlen
    if len(data) < off:
        raise KvWireError("kvwire: payload truncated inside header")
    try:
        header = json.loads(data[_PRELUDE.size:off])
    except ValueError as exc:
        raise KvWireError(f"kvwire: undecodable header: {exc}") from exc
    if not isinstance(header, dict) or not isinstance(
            header.get("planes"), list):
        raise KvWireError("kvwire: header is not a plane-table dict")
    return header, off


def decode_blocks(data: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    """Payload → (header, canonical host planes). Fully validated:
    every plane present, sized and shaped before anything is returned."""
    header, off = decode_header(data)
    planes: dict[str, np.ndarray] = {}
    for rec in header["planes"]:
        try:
            name, nbytes = rec["name"], int(rec["nbytes"])
            shape = tuple(int(d) for d in rec["shape"])
            dt = _np_dtype(str(rec["dtype"]))
        except (KeyError, TypeError, ValueError) as exc:
            raise KvWireError(
                f"kvwire: malformed plane record {rec!r}: {exc}") from exc
        if len(data) < off + nbytes:
            raise KvWireError(f"kvwire: plane {name!r} truncated")
        arr = np.frombuffer(data[off:off + nbytes], dtype=dt)
        if arr.size != int(np.prod(shape)):
            raise KvWireError(
                f"kvwire: plane {name!r} has {arr.size} elements, "
                f"shape {shape} needs {int(np.prod(shape))}")
        planes[name] = arr.reshape(shape)
        off += nbytes
    return header, planes
