"""Self-speculative decoding: prompt-lookup (n-gram) draft proposal and
per-slot acceptance control (ISSUE 5 tentpole).

Why no draft model: the serving hot path is memory-bandwidth-bound — one
decode step streams every weight byte to produce ONE token per sequence.
A verify pass over ``1 + spec_len`` positions streams the same weight
bytes, so in the bandwidth-bound regime each accepted draft token is a
nearly-free extra token. Drafts come from the request's OWN context
(prompt + generated so far): code, structured output, RAG answers and
chat histories repeat themselves constantly, and an n-gram lookup catches
exactly that — for free, for every preset, with zero extra weights to
load (the DeepServe/λScale cost driver is tokens/sec/chip, not FLOPs).

Correctness does not depend on draft quality: the engine's verify graph
emits the MODEL'S OWN tokens at every position and accepts a draft token
only where it equals the model's output, so the emitted stream is exactly
the stream classic decode would have produced (greedy parity is
bit-exact; sampled decode emits model samples, never draft inventions).
Bad drafts cost only wasted verify compute — which is what the
:class:`SlotSpecState` EWMA controller bounds: acceptance below the floor
auto-disables speculation for that request (with periodic re-probes, so a
prompt that BECOMES repetitive later gets another chance).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


class NGramProposer:
    """Prompt-lookup drafting over one request's token history.

    Keeps an index from the last-``n``-token window to the position right
    after its most recent PREVIOUS occurrence; a proposal copies the
    continuation that followed that occurrence. Longest n wins (a 3-gram
    match is far more predictive than a 1-gram one); ``min_n`` defaults to
    2 because 1-gram continuations are mostly noise that burns verify
    compute.

    The index is updated incrementally as tokens append — proposal and
    update are both O(max_n), independent of history length.
    """

    def __init__(self, tokens: list[int], max_n: int = 3, min_n: int = 2):
        self.max_n = max(1, max_n)
        self.min_n = max(1, min(min_n, self.max_n))
        self.tokens: list[int] = []
        # per n: {n-gram tuple: position AFTER its latest occurrence} plus
        # the occurrence BEFORE that — the suffix's own n-gram is always
        # the latest occurrence of itself, so proposals read the previous
        # one (the continuation that followed it last time)
        self._index: list[dict[tuple, int]] = [
            {} for _ in range(self.max_n + 1)]
        self._prev: list[dict[tuple, int]] = [
            {} for _ in range(self.max_n + 1)]
        self.extend(tokens)

    def extend(self, tokens: list[int]) -> None:
        for t in tokens:
            self.append(int(t))

    def append(self, tok: int) -> None:
        self.tokens.append(tok)
        end = len(self.tokens)
        for n in range(self.min_n, self.max_n + 1):
            if end >= n:
                key = tuple(self.tokens[end - n:end])
                old = self._index[n].get(key)
                if old is not None:
                    self._prev[n][key] = old
                self._index[n][key] = end

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the current history, or
        ``[]`` when no n-gram of the suffix has occurred before. When the
        previous occurrence sits within ``k`` tokens of the end, the
        history between it and the suffix is a cycle of period
        ``end - pos`` — the draft extrapolates that cycle instead of
        truncating, which is exactly the repeated-structure case
        (tables, code idioms, looping outputs) speculation feeds on."""
        if k <= 0:
            return []
        end = len(self.tokens)
        for n in range(self.max_n, self.min_n - 1, -1):
            if end < n:
                continue
            pos = self._prev[n].get(tuple(self.tokens[end - n:end]))
            if pos is None:
                continue
            draft = self.tokens[pos:pos + k]
            period = end - pos
            while len(draft) < k:
                draft.append(draft[len(draft) - period])
            return draft
        return []


# EWMA weight for per-window acceptance updates: ~3-window memory, so a
# request that turns repetitive (or stops being) re-rates within a few
# windows, not its whole lifetime — greedy decode drifts into and out of
# repetitive structure quickly, and a sluggish controller misses the
# profitable phase entirely
EWMA_ALPHA = 0.3


@dataclass
class SlotSpecState:
    """Per-slot speculation state: the proposer plus the acceptance EWMA
    the serve loop's window chooser reads. Starts optimistic (EWMA 1.0)
    so every request gets speculation tried; adversarial prompts decay
    below the floor within a few windows and fall back to classic
    windowed decode."""

    proposer: NGramProposer
    ewma: float = 1.0
    proposed: int = 0
    accepted: int = 0
    windows: int = 0

    def observe(self, proposed: int, accepted: int) -> None:
        self.proposed += proposed
        self.accepted += accepted
        self.windows += 1
        if proposed > 0:
            rate = accepted / proposed
            self.ewma = (1.0 - EWMA_ALPHA) * self.ewma + EWMA_ALPHA * rate


def make_slot_state(prompt: list[int],
                    max_n: int = 3) -> SlotSpecState:
    return SlotSpecState(proposer=NGramProposer(list(prompt), max_n=max_n))


def build_drafts(states: list[Optional[SlotSpecState]], active,
                 spec_len: int):
    """Draft matrix [B, spec_len] for one verify window. Slots without a
    proposal (or inactive) get zero-padding — padding never affects
    correctness (the verify graph emits the model's own tokens; a padded
    draft is just unlikely to be accepted), so the graph keeps one static
    shape for any mix of hit/miss slots. Returns (drafts, proposed_mask)
    where proposed_mask[b] is how many REAL draft tokens slot b supplied
    (EWMA accounting must not punish a slot for padding it never
    proposed)."""
    import numpy as np
    b = len(states)
    drafts = np.zeros((b, spec_len), dtype=np.int32)
    n_real = np.zeros((b,), dtype=np.int32)
    for slot, st in enumerate(states):
        if st is None or not active[slot]:
            continue
        prop = st.proposer.propose(spec_len)
        drafts[slot, :len(prop)] = prop
        n_real[slot] = len(prop)
    return drafts, n_real
