"""Mesh-sharded multi-chip serving (ISSUE 9): the topology planner and the
sharding policy objects the engine places its device state through.

``plan`` is pure host arithmetic (feasibility-priced submesh choice);
``policy`` is the only module that touches ``jax.sharding``. The engine
imports policies, never meshes — sharding lands as a policy object, not a
fork of the engine.
"""

from .plan import (Topology, TopologyPlan, candidate_topologies,
                   parse_topology, plan_topology, resolve_topology,
                   topology_from_env)
from .policy import MeshPolicy, SingleDevicePolicy, make_policy

__all__ = ["Topology", "TopologyPlan", "candidate_topologies",
           "parse_topology", "plan_topology", "resolve_topology",
           "topology_from_env", "MeshPolicy", "SingleDevicePolicy",
           "make_policy"]
