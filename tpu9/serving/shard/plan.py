"""Topology planner: pick a tp / tp×fsdp submesh shape per preset (ISSUE 9).

The MULTICHIP probes proved the mechanisms (tp=2 serving forward, paged KV
under tp, dp×fsdp×tp meshes); this module decides the SHAPE. Planning is
pure host arithmetic over ``feasibility.py``'s exact HBM pricing — weights
(quantization-aware, via ``jax.eval_shape`` over the real init fns) + KV
pool + scratch + headroom per chip — so a deployment either provably fits
its submesh or is rejected with numbers, never an OOM at bind time.

Rules:
- candidate chip counts are powers of two up to the slice size (ICI meshes
  come in powers of two; a 3-chip submesh has no layout);
- ``tp`` takes as many chips as divide ``n_kv_heads`` exactly — the paged
  KV pool shards on the head axis and a non-dividing tp would replicate KV
  (all the HBM cost, none of the capacity win); excess chips go to
  ``fsdp``, which shards weights only;
- the SMALLEST chip count that fits wins: serving economics is tokens/sec
  per chip, and spreading a model that fits N chips over 2N halves it.

Explicit overrides (``load_engine(topology=...)`` / ``TPU9_TOPOLOGY``)
bypass the planner entirely — ``parse_topology`` is the shared syntax.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Topology:
    """A serving submesh shape: ``tp`` chips tensor-parallel (innermost,
    fastest ICI; shards weights AND the paged-KV head axis) × ``fsdp``
    chips weight-sharded on top. ``1x1`` is the single-chip engine and
    must behave bit-identically to a topology-oblivious build."""

    tp: int = 1
    fsdp: int = 1

    def __post_init__(self) -> None:
        if self.tp < 1 or self.fsdp < 1:
            raise ValueError(f"topology axes must be >= 1, got {self}")

    @property
    def n_chips(self) -> int:
        return self.tp * self.fsdp

    @property
    def is_single(self) -> bool:
        return self.n_chips == 1

    def as_dict(self) -> dict:
        return {"tp": self.tp, "fsdp": self.fsdp, "n_chips": self.n_chips}

    def __str__(self) -> str:
        return f"{self.tp}x{self.fsdp}"


def parse_topology(value: "str | Topology | None") -> Optional[Topology]:
    """Parse a topology override: ``"2"`` (tp only), ``"2x4"`` (tp×fsdp),
    or ``"tp=2,fsdp=4"``. ``None``/``""`` → None (caller decides the
    default); a :class:`Topology` passes through."""
    if value is None:
        return None
    if isinstance(value, Topology):
        return value
    s = str(value).strip().lower()
    if not s:
        return None
    if "=" in s:
        axes = {"tp": 1, "fsdp": 1}
        for part in s.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in axes:
                raise ValueError(f"unknown topology axis {k!r} in {value!r}"
                                 " (tp/fsdp)")
            axes[k] = int(v)
        return Topology(**axes)
    if "x" in s:
        tp_s, _, fsdp_s = s.partition("x")
        return Topology(tp=int(tp_s), fsdp=int(fsdp_s))
    return Topology(tp=int(s))


def topology_from_env(env: str = "TPU9_TOPOLOGY") -> Optional[Topology]:
    """The runner-facing override: ``TPU9_TOPOLOGY=2x1`` etc. ``auto`` is
    NOT resolved here — it needs a slice spec, which only the deploy-time
    caller has."""
    raw = os.environ.get(env, "")
    if not raw or raw.strip().lower() == "auto":
        return None
    return parse_topology(raw)


@dataclass(frozen=True)
class TopologyPlan:
    """A planner decision plus the HBM arithmetic that justifies it
    (``budget`` is the winning submesh's :class:`HbmBudget`; ``rejected``
    records each smaller candidate and why it lost — the deploy log line
    that makes 'why 4 chips?' answerable)."""

    preset: str
    topology: Topology
    budget: Any                      # serving.feasibility.HbmBudget
    rejected: tuple = ()             # ((Topology, required_gb, have_gb), ..)

    def as_dict(self) -> dict:
        return {"preset": self.preset, **self.topology.as_dict(),
                "budget": self.budget.as_dict(),
                "rejected": [
                    {**t.as_dict(), "required_gb_per_chip": req,
                     "hbm_gb_per_chip": have}
                    for t, req, have in self.rejected]}


def candidate_topologies(n_kv_heads: int, max_chips: int) -> list[Topology]:
    """Power-of-two chip counts, smallest first; per count, tp takes the
    largest factor that divides ``n_kv_heads`` (exact KV head sharding),
    fsdp the rest."""
    out: list[Topology] = []
    n = 1
    while n <= max_chips:
        tp = math.gcd(n, n_kv_heads)
        out.append(Topology(tp=tp, fsdp=n // tp))
        n *= 2
    return out


def plan_topology(preset: str, tpu: "str | Any", *, max_batch: int = 8,
                  max_seq_len: int = 2048, quantize: "str | None" = None,
                  kv_quant: bool = False,
                  overhead_frac: float = 0.10) -> TopologyPlan:
    """Smallest power-of-two submesh of ``tpu`` that provably serves
    ``preset``. Raises :class:`InfeasibleDeployment` (with the full
    arithmetic of the LARGEST candidate) when even the whole slice cannot
    hold it — same failure surface as ``validate_llm_deployment``."""
    from ..feasibility import InfeasibleDeployment, hbm_budget
    from ..presets import resolve_preset
    from ...types import parse_tpu_spec
    cfg, _ = resolve_preset(preset, quantize)
    spec = parse_tpu_spec(tpu) if isinstance(tpu, str) else tpu
    if spec is None:
        raise ValueError("plan_topology needs a TPU spec")

    rejected: list = []
    budget = None
    for topo in candidate_topologies(cfg.n_kv_heads, spec.chips):
        budget = hbm_budget(preset, spec, max_batch=max_batch,
                            max_seq_len=max_seq_len, tp=topo.tp,
                            fsdp=topo.fsdp, overhead_frac=overhead_frac,
                            quantize=quantize, kv_quant=kv_quant)
        if budget.fits:
            return TopologyPlan(preset=preset, topology=topo, budget=budget,
                                rejected=tuple(rejected))
        rejected.append((topo, round(budget.required_gb_per_chip, 3),
                         budget.hbm_per_chip_gb))
    d = budget.as_dict()
    raise InfeasibleDeployment(
        f"{preset} does not fit {spec.name} at any submesh up to "
        f"{spec.chips} chips: largest candidate tp={d['tp']} "
        f"fsdp={d['fsdp']} still needs {d['required_gb_per_chip']} GB/chip "
        f"(weights {d['weight_gb_per_chip']} + KV {d['kv_gb_per_chip']} + "
        f"scratch {d['scratch_gb_per_chip']}) against "
        f"{d['hbm_per_chip_gb']} GB. Remedies: int8 weights, int8 KV, "
        f"smaller max_batch/max_seq_len, or a larger slice.")


def resolve_topology(topology: "str | Topology | None" = None,
                     preset: str = "", tpu: "str | Any | None" = None,
                     **plan_kw) -> Topology:
    """Override chain for the serving stack: explicit arg → TPU9_TOPOLOGY
    env → planner (when a slice spec is known) → single chip. The string
    ``"auto"`` forces the planner (and then REQUIRES ``tpu``)."""
    want_auto = isinstance(topology, str) \
        and topology.strip().lower() == "auto"
    if not want_auto:
        explicit = parse_topology(topology)
        if explicit is not None:
            return explicit
        env = topology_from_env()
        if env is not None:
            return env
        want_auto = (os.environ.get("TPU9_TOPOLOGY", "")
                     .strip().lower() == "auto")
    if want_auto or (topology is None and tpu is not None and preset):
        if not (tpu and preset):
            raise ValueError(
                "topology='auto' needs a preset and a TPU spec to plan "
                "against (set topology explicitly, e.g. '2x1')")
        return plan_topology(preset, tpu, **plan_kw).topology
    return Topology(1, 1)
